"""Expert-parallel MoE MLP (SURVEY C9): GShard-style top-k capacity routing.

TPU-native formulation: experts live in a single stacked parameter
(E, D, H) sharded over the ``expert`` mesh axis; token dispatch/combine are
einsums against one-hot dispatch tensors, so GSPMD lowers the expert
exchange to ``all_to_all`` on ICI — no manual send/recv.

**Grouped dispatch** (the GShard paper's GSEC layout): the token stream is
split into G independent routing groups, each with its own capacity
``C_g = capacity_factor * (N/G) * k / E``. The dispatch/combine tensors are
``[G, S, E, C_g]`` — total memory ``N * E * C_g``, i.e. **G× smaller** than
the ungrouped ``[N, E, C]`` formulation (at GPT-2-medium MoE shapes,
N=4096 / E=64 / cf=1.25 / k=2 → C=160: the ungrouped bf16 dispatch +
fp32 combine pair is ~252 MB per layer, G=8 cuts it to ~31 MB; measured
deltas in docs/perf_playbook.md). Groups default to the mesh's
batch-shard count, so each data shard routes its own tokens and the group
dim stays batch-sharded through every einsum. Per-group capacity is the
standard practice trade: a token can be dropped because *its group* is
over capacity even if another group has room (residual carries it, as with
any capacity drop).

Router math in fp32. Load-balance aux loss per GShard/Switch over ALL k
assignment slots, plus the ST-MoE router z-loss (mean log²-sum-exp of the
router logits) that keeps logits from drifting into bf16-hostile ranges.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig
from frl_distributed_ml_scaffold_tpu.dist.mesh import (
    BATCH_AXES,
    current_mesh_env,
)


def _num_groups(moe, n: int, b: int, train: bool) -> int:
    """Routing-group count for ``n`` tokens (batch dim ``b``).

    Explicit config must divide the token count in the TRAINING path —
    a silent gcd snap there would change per-group capacity semantics
    (different drop boundaries) with no signal, so it raises instead. In
    the decode path (train=False, tiny n = batch at one token per
    sequence) ``gcd`` snaps to the nearest divisor: a hard divisibility
    error would make every grouped-MoE checkpoint un-generatable.

    Auto (0) follows the mesh's batch sharding so each data shard routes
    its own tokens — snapped to ``gcd(b, shards)`` so the group dim always
    aligns with the batch dim (never cuts a group mid-sequence) and stays
    batch-sharded through every einsum; since g | b and n = b*t, g | n."""
    if moe.num_groups > 0:
        if train and b % moe.num_groups != 0:
            # Divide the BATCH dim, not merely n=b*t: a group that cuts a
            # sequence breaks the batch alignment the einsum sharding
            # relies on (same invariant as the auto path below); g | b
            # also gives g | n since n = b*t.
            raise ValueError(
                f"moe.num_groups={moe.num_groups} does not divide the "
                f"training batch dim b={b} (token count n={n}); a silent "
                "snap would change per-group capacity/drop semantics, and "
                "groups must align with the batch dim to stay "
                "batch-sharded. Pick a divisor of the batch size or use "
                "num_groups=0 (auto)."
            )
        # The gcd snap only serves decode (train=False, tiny n).
        return moe.num_groups if train else math.gcd(n, moe.num_groups)
    env = current_mesh_env()
    if env is None:
        return 1
    shards = 1
    for a in BATCH_AXES:
        shards *= env.mesh.shape.get(a, 1)
    return math.gcd(b, shards)


class MoEMlp(nn.Module):
    config: GPTConfig
    dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        moe = cfg.moe
        d = cfg.hidden_dim
        hidden = d * cfg.mlp_ratio
        e, k = moe.num_experts, moe.top_k
        if moe.dispatch not in ("einsum", "sort"):
            raise ValueError(
                f"moe.dispatch={moe.dispatch!r}: expected 'einsum' or 'sort'"
            )
        b, t, _ = x.shape
        n = b * t
        g = _num_groups(moe, n, b, train)
        s = n // g
        capacity = max(1, int(moe.capacity_factor * s * k / e))
        # Cast to the compute dtype here (the dense MLP gets this implicitly
        # from nn.Dense(dtype=...)); expert math below runs in this dtype so
        # the residual sum keeps the block's carry dtype stable under scan.
        xf = x.reshape(g, s, d).astype(self.dtype)

        # Router (fp32): probabilities over experts per token.
        router_logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32)
        )
        probs = jax.nn.softmax(router_logits, axis=-1)  # (G, S, E)
        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (G, S, k)
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

        # Position-in-expert via per-group cumulative counts, slot by slot
        # (slot-major: every token's first choice is seated before any
        # second choice, per GShard). The seating is SHARED by both
        # dispatch formulations, so routing/drop semantics are identical
        # and `test_moe_sorted_matches_einsum` can pin exact equivalence.
        pos_toks, keeps = [], []
        prev_counts = jnp.zeros((g, e), jnp.int32)
        for slot in range(k):
            onehot = jax.nn.one_hot(gate_idx[..., slot], e, dtype=jnp.int32)
            pos = jnp.cumsum(onehot, axis=1) - 1 + prev_counts[:, None, :]
            prev_counts = prev_counts + onehot.sum(axis=1)
            pos_tok = (pos * onehot).sum(-1)  # (G, S)
            pos_toks.append(pos_tok)
            keeps.append(pos_tok < capacity)

        # Expert computation: stacked params, expert axis shardable. The
        # group dim rides the batch sharding; the E dim the expert axis.
        wi = self.param(
            "wi", nn.initializers.normal(stddev=0.02), (e, d, hidden)
        )
        wo = self.param(
            "wo", nn.initializers.normal(stddev=0.02), (e, hidden, d)
        )

        if moe.dispatch == "sort":
            # Ragged (scatter/gather) exchange: seat indices scattered
            # into the [E*C] slot table, tokens gathered by index —
            # ~zero exchange MACs vs the einsum pair's O(S*E*C*D), which
            # at audited shapes costs as much as the expert FFN itself
            # (docs/perf_playbook.md "Dispatch FLOPs"). Sentinel row s /
            # slot e*c catches drops and empty seats (gathered as zeros,
            # scattered into the void via mode='drop').
            gi = jnp.arange(g)[:, None]
            token_idx = jnp.broadcast_to(jnp.arange(s)[None, :], (g, s))
            src = jnp.full((g, e * capacity), s, jnp.int32)
            for slot in range(k):
                dest = jnp.where(
                    keeps[slot],
                    gate_idx[..., slot] * capacity + pos_toks[slot],
                    e * capacity,
                )
                src = src.at[gi, dest].set(token_idx, mode="drop")
            x_pad = jnp.concatenate(
                [xf, jnp.zeros((g, 1, d), self.dtype)], axis=1
            )
            expert_in = (
                x_pad[gi, src]  # (G, E*C, D)
                .reshape(g, e, capacity, d)
                .transpose(1, 0, 2, 3)  # (E, G, C, D)
            )
            h = jax.nn.gelu(
                jnp.einsum("egcd,edh->egch", expert_in, wi.astype(self.dtype))
            )
            expert_out = jnp.einsum("egch,ehd->egcd", h, wo.astype(self.dtype))
            out_pad = jnp.concatenate(
                [
                    expert_out.transpose(1, 0, 2, 3).reshape(
                        g, e * capacity, d
                    ),
                    jnp.zeros((g, 1, d), self.dtype),
                ],
                axis=1,
            )
            y = jnp.zeros((g, s, d), self.dtype)
            for slot in range(k):
                idx = jnp.where(
                    keeps[slot],
                    gate_idx[..., slot] * capacity + pos_toks[slot],
                    e * capacity,
                )
                w = jnp.where(
                    keeps[slot], gate_vals[..., slot], 0.0
                ).astype(self.dtype)
                y = y + out_pad[gi, idx] * w[..., None]
        else:
            # One-hot einsum exchange (GShard): GSPMD turns the
            # dispatch/combine einsums into all_to_all on ICI.
            dispatch = jnp.zeros((g, s, e, capacity), self.dtype)
            combine = jnp.zeros((g, s, e, capacity), jnp.float32)
            for slot in range(k):
                onehot = jax.nn.one_hot(
                    gate_idx[..., slot], e, dtype=jnp.int32
                )
                pos_oh = jax.nn.one_hot(
                    pos_toks[slot], capacity, dtype=self.dtype
                )
                slot_dispatch = (
                    onehot.astype(self.dtype)[..., None]
                    * pos_oh[..., None, :]
                    * keeps[slot].astype(self.dtype)[..., None, None]
                )
                dispatch = dispatch + slot_dispatch
                combine = combine + slot_dispatch.astype(
                    jnp.float32
                ) * gate_vals[..., slot].astype(jnp.float32)[..., None, None]
            expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, xf)
            h = jax.nn.gelu(
                jnp.einsum("egcd,edh->egch", expert_in, wi.astype(self.dtype))
            )
            expert_out = jnp.einsum(
                "egch,ehd->egcd", h, wo.astype(self.dtype)
            )
            y = jnp.einsum(
                "gsec,egcd->gsd", combine.astype(self.dtype), expert_out
            )  # and back

        # GShard load-balance loss, E * sum_e(frac_tokens_e * mean_prob_e),
        # with frac counting ALL k assignment slots (each slot contributes
        # 1/k so a perfectly uniform router scores frac_e = 1/E exactly as
        # in the top-1 form). prev_counts already holds the slot-summed
        # per-expert counts; gate_idx is integer so frac carries no
        # gradient either way — aux gradients flow through mean_prob.
        frac = prev_counts.sum(0).astype(jnp.float32) / (g * s * k)
        mean_prob = jnp.mean(probs, axis=(0, 1))
        aux = moe.router_aux_loss * e * jnp.sum(frac * mean_prob)
        # ST-MoE router z-loss: penalizes large router logits (bf16-unsafe
        # and softmax-saturating) without touching the routing decision.
        if moe.router_z_loss > 0.0:
            z = jax.nn.logsumexp(router_logits, axis=-1)  # (G, S)
            aux = aux + moe.router_z_loss * jnp.mean(z * z)

        return y.reshape(b, t, d), aux
