"""GPT-2 transformer LM (BASELINE config 4: GPT-2-medium, ZeRO-1 + accum).

The flagship model and the carrier for every task-required parallelism
(SURVEY C6–C9):

- **TP**: q/k/v/fc_in kernels column-split, out/fc_out row-split over the
  ``model`` axis — Megatron layout, expressed purely as ``gpt_tp_rules()``
  regex → PartitionSpec (the model code itself is strategy-free; GSPMD
  inserts the per-layer allreduces).
- **SP**: ``attention="ring"`` routes through the ring-attention op
  (ops/ring_attention.py) for sequence-sharded long context;
  ``"ulysses"`` does the all_to_all head↔seq reshard around dense attention.
- **EP**: ``moe.num_experts > 0`` swaps the MLP for the expert-parallel MoE
  block (models/moe.py).

TPU-first details: layers stacked with ``nn.scan`` (one compiled block body
regardless of depth — compile time stays flat at 24 layers), softmax and
LayerNorm in fp32, everything else in the policy compute dtype (bf16 on the
MXU), weight-tied LM head.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig
from frl_distributed_ml_scaffold_tpu.parallel.partition import PartitionRules
from frl_distributed_ml_scaffold_tpu.precision import Policy


def gpt_tp_rules(pipelined: bool = False, circular: bool = False) -> PartitionRules:
    """Megatron column/row sharding (SURVEY C6). Kernels carry a leading
    layer dim from nn.scan stacking, hence the extra ``None``; under
    pipeline parallelism they carry ``[stage, layer_in_stage, ...]`` and the
    stage dim shards over ``pipe`` (SURVEY C7). The circular schedule adds a
    leading virtual-repeat dim: ``[repeat, stage, layer_in_group, ...]``."""
    if circular:
        pre: tuple = (None, "pipe", None)
    elif pipelined:
        pre = ("pipe", None)
    else:
        pre = (None,)
    rules: tuple = (
        (r"blocks/attn/(query|key|value)/kernel", P(*pre, None, "model")),
        (r"blocks/attn/(query|key|value)/bias", P(*pre, "model")),
        (r"blocks/attn/out/kernel", P(*pre, "model", None)),
        (r"blocks/mlp/fc_in/kernel", P(*pre, None, "model")),
        (r"blocks/mlp/fc_in/bias", P(*pre, "model")),
        (r"blocks/mlp/fc_out/kernel", P(*pre, "model", None)),
        (r"blocks/moe/wi", P(*pre, "expert", None, "model")),
        (r"blocks/moe/wo", P(*pre, "expert", "model", None)),
        (r"blocks/moe/router/kernel", P(*pre, None, None)),
        (r"wte/embedding", P("model", None)),
    )
    if circular:
        # Everything else inside the stacked blocks (LayerNorm scales etc.)
        # still lives on its stage. Placed last — first match wins.
        rules = rules + ((r"blocks/", P(None, "pipe")),)
    elif pipelined:
        rules = rules + ((r"blocks/", P("pipe")),)
    return PartitionRules(rules=rules)


def _train_block_stack(cfg: GPTConfig, *, length: int, hooks=None):
    """The scanned TRAINING-mode Block stack: blockwise param-gather hook
    (``nn.map_variables``) + per-block remat wrap + ``nn.scan``, shared by
    the monolithic ``GPT`` and the per-stage ``GptStage`` (MPMD pipeline,
    ISSUE 14) so the two paths cannot drift. Returns the transformed
    CLASS; the caller instantiates it with ``name="blocks"`` (decode
    builds its own plain scan — caches/hooks never mix)."""
    block_cls = Block
    if hooks is not None:
        # Gather INSIDE the scan body (one layer's slice per iteration —
        # the blockwise schedule) and inside the remat region below (so
        # recompute re-gathers instead of saving full params).
        # map_variables(init=False): param creation still sees the raw
        # sharded tree, keeping init and checkpoint layouts identical to
        # the unhooked model.
        block_cls = nn.map_variables(
            block_cls,
            "params",
            trans_in_fn=hooks.block_hook,
            init=False,
        )
    if cfg.block_remat != "none" or hooks is not None:
        # Per-layer remat (config 3's activation checkpointing at the
        # granularity that matters under nn.scan): checkpoint each
        # scanned body so the backward re-derives one block's internals
        # at a time instead of holding all L layers'. prevent_cse=False
        # is the documented setting under scan — the scan boundary
        # already stops the CSE that remat's default guards against, and
        # leaving it True blocks XLA optimizations for nothing.
        if hooks is not None:
            # Same three modes, with gathered params always excluded
            # from the saved set (GATHER_NAME tag).
            from frl_distributed_ml_scaffold_tpu.parallel.fsdp_overlap import (
                overlap_remat_policy,
            )

            policy = overlap_remat_policy(cfg.block_remat)
        elif cfg.block_remat == "full":
            policy = None
        elif cfg.block_remat == "save_attn":
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out"
            )
        else:
            raise KeyError(
                f"unknown model.block_remat={cfg.block_remat!r} "
                "(none | full | save_attn)"
            )
        block_cls = nn.remat(block_cls, prevent_cse=False, policy=policy)
    return nn.scan(
        block_cls,
        length=length,
        variable_axes={"params": 0, "cache": 0},
        split_rngs={"params": True, "dropout": True},
    )


def mpmd_stage_params(cfg: GPTConfig, params, num_stages: int):
    """Slice a PLAIN-layout GPT params tree into per-stage trees for the
    MPMD pipeline backend (ISSUE 14): ``{"stage_j": ...}`` where stage
    ``j`` owns ``blocks`` leaves ``[L/S, ...]`` (rows ``[j*L/S,
    (j+1)*L/S)`` of the plain ``[L, ...]`` stack — a pure slice, no
    transpose), the FIRST stage additionally owns the embedding tables
    (``wte``/``wpe`` — and with them the weight-tied LM head's master
    copy), and the LAST stage owns ``ln_f``. Inverse:
    ``mpmd_merge_params``; ``unstack_pipeline_params`` accepts either
    stacked layout so decode/export paths need no config surgery."""
    if "blocks" not in params:
        raise ValueError(
            "mpmd_stage_params expects the PLAIN-layout params tree "
            "(blocks leaves [L, ...]); restack pipeline-trained params "
            "via unstack_pipeline_params first"
        )
    L, s = cfg.num_layers, num_stages
    if s < 2:
        raise ValueError(f"MPMD stage slicing needs >= 2 stages, got {s}")
    if L % s:
        raise ValueError(f"{L} layers not divisible by {s} stages")
    lps = L // s
    head_keys = {"ln_f"}
    out = {}
    for j in range(s):
        tree = {
            "blocks": jax.tree.map(
                lambda l, _j=j: l[_j * lps : (_j + 1) * lps],
                params["blocks"],
            )
        }
        if j == 0:
            # Everything outside the block stack that is not the final
            # norm feeds the input side (wte/wpe today; future input-side
            # params land here by default).
            for k, v in params.items():
                if k not in ("blocks", *head_keys):
                    tree[k] = v
        if j == s - 1:
            for k in head_keys:
                if k in params:
                    tree[k] = params[k]
        out[f"stage_{j}"] = tree
    return out


def mpmd_merge_params(cfg: GPTConfig, stage_params):
    """Merge MPMD per-stage trees (``mpmd_stage_params`` layout) back to
    the plain-stack params tree — blocks leaves concatenate along the
    layer dim in stage order; wte/wpe come from stage 0, ln_f from the
    last stage."""
    stages = sorted(
        (k for k in stage_params if k.startswith("stage_")),
        key=lambda k: int(k.split("_", 1)[1]),
    )
    if len(stages) < 2 or stages != [f"stage_{j}" for j in range(len(stages))]:
        raise ValueError(
            f"not an MPMD stage-params tree (keys: {sorted(stage_params)})"
        )
    out = {}
    for k, v in stage_params[stages[0]].items():
        if k != "blocks":
            out[k] = v
    for k, v in stage_params[stages[-1]].items():
        if k != "blocks":
            out[k] = v
    out["blocks"] = jax.tree.map(
        lambda *ls: jnp.concatenate(ls, axis=0),
        *[stage_params[k]["blocks"] for k in stages],
    )
    return out


class GptStage(nn.Module):
    """One MPMD pipeline stage as a standalone per-stage program body
    (ISSUE 14): a contiguous run of ``num_layers`` Blocks, with the
    embedding front (``wte``/``wpe`` + dropout) on the FIRST stage and
    the final ``ln_f`` on the LAST. Param names match the monolithic
    ``GPT`` exactly, so per-stage trees are pure slices of the plain
    stack (``mpmd_stage_params``) and checkpoints restack losslessly.

    The weight-tied LM head is deliberately NOT applied here: the last
    stage returns ``ln_f``'d FEATURES, and the loss program receives the
    first stage's embedding table as an explicit cross-stage input — the
    tied-embedding transfer every MPMD system carries (its gradient
    rides the reverse transfer back to stage 0's master copy).

    ``param_hooks``/``tp_overlap`` take the same overlap-schedule hooks
    as ``GPT`` (parallel/schedule.py ``hooked_model`` clones either
    attribute): the fsdp block gathers and TP rings lower INSIDE the
    stage program, where they compose exactly as in the monolithic scan
    body — per-stage programs have no stage vmap for them to collide
    with."""

    config: GPTConfig
    policy: Policy
    num_layers: int
    first: bool = False
    last: bool = False
    param_hooks: Any = None
    tp_overlap: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False):
        cfg = self.config
        dtype = self.policy.compute_dtype
        if self.first:
            # Same modules, names, initializers, and dtype flow as GPT's
            # embedding front — stage 0's subtree IS the plain tree's.
            wte = nn.Embed(
                cfg.vocab_size,
                cfg.hidden_dim,
                dtype=dtype,
                embedding_init=nn.initializers.normal(stddev=0.02),
                name="wte",
            )
            wpe = self.param(
                "wpe",
                nn.initializers.normal(stddev=0.02),
                (cfg.seq_len, cfg.hidden_dim),
            )
            t = x.shape[1]
            x = wte(x) + wpe[:t].astype(dtype)
            x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        stack_cls = _train_block_stack(
            cfg, length=self.num_layers, hooks=self.param_hooks
        )
        blocks = stack_cls(
            cfg, dtype, train, False, self.tp_overlap, 0, 0, 0,
            name="blocks",
        )
        (x, _aux), _ = blocks((x, jnp.zeros((), jnp.float32)), None)
        if self.last:
            x = nn.LayerNorm(
                dtype=jnp.float32, epsilon=cfg.layer_norm_epsilon,
                name="ln_f",
            )(x)
        return x


def unstack_pipeline_params(cfg: GPTConfig, params):
    """Restack pipeline-trained block params into the plain-stack layout.

    Pipeline training stores block weights stage-stacked — GPipe as
    ``pipeline/ticks/blocks`` leaves ``[S, L/S, ...]``, circular as
    ``pipeline/blocks`` leaves ``[v, S, L/(S*v), ...]`` — while the decode
    path's ``nn.scan`` stack expects ``blocks`` leaves ``[L, ...]``. Both
    stacked layouts enumerate layers in row-major order of their leading
    dims (stage j holds contiguous layers; circular virtual stage
    ``r*S + j`` is row ``[r, j]``), so the restack is a pure reshape per
    leaf — no transpose, no new compute path. Returns a params tree a
    ``pipeline_stages=1`` model of the same config applies directly.
    """
    if "pipeline" not in params:
        if "stage_0" in params:
            # MPMD per-stage layout (ISSUE 14): merge, don't reshape —
            # stage trees are plain-stack slices by construction.
            return mpmd_merge_params(cfg, params)
        raise ValueError(
            "params carry no 'pipeline' subtree — already plain-stacked?"
        )
    pipe = params["pipeline"]
    # GPipe nests under the scanned tick module; circular owns the stacked
    # pytree directly.
    blocks = pipe["ticks"]["blocks"] if "ticks" in pipe else pipe["blocks"]
    lead = 2 if "ticks" in pipe else 3
    L = cfg.num_layers

    def restack(leaf):
        import numpy as np

        if int(np.prod(leaf.shape[:lead])) != L:
            raise ValueError(
                f"stacked leaf {leaf.shape} does not fold into "
                f"{L} layers ({lead} leading dims)"
            )
        return leaf.reshape((L,) + leaf.shape[lead:])

    out = {k: v for k, v in params.items() if k != "pipeline"}
    out["blocks"] = jax.tree.map(restack, blocks)
    return out


def _masked_dense_attention(q, k, v, mask):
    """Dense attention with an explicit mask ([Tq, Tk] shared or
    [B, Tq, Tk] per-row), fp32 softmax — the same numerics as
    ops.dense_attention, used by the KV-cache decode path where causality
    is against *absolute* positions in the cache, not positions within the
    query window. The per-row form carries ragged-prompt occupancy."""
    hd = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    mask = mask[:, None] if mask.ndim == 3 else mask[None, None]
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        probs.astype(q.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _constrain_kv_pool(x: jnp.ndarray) -> jnp.ndarray:
    """Pin a PAGED cache leaf — [N, bs, H, hd] K/V pool blocks or their
    [N, bs, H] scale pools — model-sharded over the mesh's ``model`` axis
    (heads on axis 2, the same Megatron split as ``_constrain_kv_cache``)
    and REPLICATED over the batch axes: pool blocks are shared across
    slot rows (that is what multiplies concurrency), so a batch-sharded
    pool would scatter a row's blocks across data shards and every table
    lookup would become a cross-shard gather."""
    from frl_distributed_ml_scaffold_tpu.dist.mesh import current_mesh_env

    env = current_mesh_env()
    if env is None or env.axis_size("model") <= 1:
        return x
    if x.ndim < 3 or x.shape[2] % env.axis_size("model") != 0:
        return x
    from jax.sharding import NamedSharding

    spec = P(None, None, "model", *([None] * (x.ndim - 3)))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(env.mesh, spec)
    )


def _constrain_kv_cache(x: jnp.ndarray) -> jnp.ndarray:
    """Pin a cache leaf — [B, S, H, hd] K/V values or their [B, S, H]
    quantization scales — model-sharded over the mesh's ``model`` axis
    (heads split on axis 2 either way — the Megatron layout the
    projection kernels already carry), batch over the batch axes when
    divisible.

    This is what keeps multi-chip serving from silently running the cache
    replicated: prefill EMITS the cache in this layout and every decode
    step consumes and re-emits it in the same layout, so no monolithic
    reshard appears at the prefill->decode handoff (jaxpr-pinned in
    tests/test_serving.py, the tp_overlap pin style)."""
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        BATCH_AXES,
        current_mesh_env,
    )

    env = current_mesh_env()
    if env is None or env.axis_size("model") <= 1:
        return x
    if x.ndim < 3 or x.shape[2] % env.axis_size("model") != 0:
        return x
    batch = BATCH_AXES if x.shape[0] % env.batch_axis_size == 0 else None
    from jax.sharding import NamedSharding

    spec = P(batch, None, "model", *([None] * (x.ndim - 3)))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(env.mesh, spec)
    )


class CausalSelfAttention(nn.Module):
    config: GPTConfig
    dtype: Any
    # Collective-matmul TP schedule (parallel/tp_overlap.py TpHooks): when
    # set, QKV share one bidirectional all-gather-matmul ring (the first
    # projection streams the sequence shards in under its own compute and
    # hands the assembled copy to its siblings) and the out projection is
    # a matmul-reduce-scatter ring instead of matmul+allreduce. Params are
    # untouched — the hooks ride nn.Dense's injectable dot_general.
    tp: Any = None
    # Decode KV-cache capacity (0 = config.seq_len): serving buckets the
    # cache to a power of two covering prompt+budget so short requests
    # stop paying full-context cache traffic (serving/engine.py policy).
    cache_len: int = 0
    # Paged decode cache (ISSUE 10; 0 = contiguous per-row cache): K/V
    # live in a shared pool of kv_pool_blocks fixed-size blocks instead
    # of [B, S] stacks; the per-row block table arrives via the scan
    # carry (serving/engine.py owns allocation and the tables).
    kv_block_size: int = 0
    kv_pool_blocks: int = 0

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        *,
        train: bool,
        decode: bool = False,
        lengths: jnp.ndarray | None = None,
        block_tables: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        cfg = self.config
        d = cfg.hidden_dim
        h = cfg.num_heads
        hd = d // h
        tp = None if decode else self.tp
        qkv_dg = tp.qkv_context().dot_general if tp is not None else None
        out_dg = tp.mrs_dot_general if tp is not None else None
        if tp is not None:
            # Pre-cast to the compute dtype so flax's per-Dense
            # promote_dtype is an identity: the shared-QKV ring cache keys
            # on input-object identity, and under bf16_mixed the fp32
            # LayerNorm output would otherwise become THREE distinct cast
            # tracers — three gather rings instead of one. Numerically a
            # no-op (Dense performs this exact cast internally).
            x = x.astype(self.dtype)
        q = nn.Dense(d, dtype=self.dtype, name="query", dot_general=qkv_dg)(x)
        k = nn.Dense(d, dtype=self.dtype, name="key", dot_general=qkv_dg)(x)
        v = nn.Dense(d, dtype=self.dtype, name="value", dot_general=qkv_dg)(x)
        b, t, _ = x.shape
        q = q.reshape(b, t, h, hd)
        k = k.reshape(b, t, h, hd)
        v = v.reshape(b, t, h, hd)

        if decode:
            # Incremental decoding: append this call's K/V at each row's
            # write position and attend over the occupied cache prefix.
            # The flash/ring/ulysses training kernels are pointless at
            # decode shapes (q is one token), so every attention mode
            # shares this path; single-token steps route through
            # ops/decode_attention (flash-decode kernel or its
            # identical-numerics dense fallback, per cfg.decode_attention).
            s = self.cache_len or cfg.seq_len
            # Quantized cache (cfg.kv_cache_quant): K/V live in the 1-byte
            # format with per-(row, position, head) bf16 scales in sibling
            # cache vars. Each written token quantizes ONCE, over its own
            # head vector — cache entries are never re-quantized, so the
            # values a position contributes are identical at every later
            # step and in every bucket size.
            quant = cfg.kv_cache_quant != "none"
            if quant:
                from frl_distributed_ml_scaffold_tpu.ops.quantization import (
                    lowp_dtype,
                )

                cache_dtype = lowp_dtype(cfg.kv_cache_quant)
            else:
                cache_dtype = self.dtype
            if self.kv_block_size > 0:
                # PAGED cache (ISSUE 10): K/V live in a POOL of
                # fixed-size blocks shared by every row; this row's
                # logical block j is physical pool block
                # block_tables[b, j]. Only single-token steps run paged —
                # prefill stays contiguous (serving/engine.py grafts the
                # prefilled blocks into the pool, moving exactly the
                # blocks that change owner). Shared-prefix blocks are
                # immutable by construction: a row's writes land at
                # positions >= its private suffix, and the engine's
                # copy-on-write admission never maps a shared block
                # there.
                if block_tables is None:
                    raise ValueError(
                        "kv_block_size set but no block_tables reached "
                        "the attention cache — the decode carry must "
                        "thread them"
                    )
                bs_blk, nb = self.kv_block_size, self.kv_pool_blocks
                ck = self.variable(
                    "cache", "key_pool", jnp.zeros,
                    (nb, bs_blk, h, hd), cache_dtype,
                )
                cv = self.variable(
                    "cache", "value_pool", jnp.zeros,
                    (nb, bs_blk, h, hd), cache_dtype,
                )
                if quant:
                    ksc = self.variable(
                        "cache", "key_pool_scale", jnp.zeros,
                        (nb, bs_blk, h), jnp.bfloat16,
                    )
                    vsc = self.variable(
                        "cache", "value_pool_scale", jnp.zeros,
                        (nb, bs_blk, h), jnp.bfloat16,
                    )
                ci = self.variable(
                    "cache", "cache_index", jnp.zeros, (b,), jnp.int32
                )
                idx = ci.value  # [B]
                # Physical write target for the j-th tile column: block
                # tbl[(idx + j) // bs], offset (idx + j) % bs. Retired
                # slots point at the reserved trash block 0 (and their
                # index keeps advancing), so the lookup clamps to the
                # table width instead of trusting idx to stay inside the
                # logical capacity — for the verify tile (t > 1, ISSUE
                # 11) the same clamp also routes DRAFT positions beyond
                # the row's allocated blocks into the trash block: the
                # engine only appends blocks through each row's real
                # draft count, and positions past it are padding whose
                # scores are never accepted.
                m_tbl = block_tables.shape[1]
                offs = idx[:, None] + jnp.arange(t)[None, :]  # [B, t]
                phys = jnp.take_along_axis(
                    block_tables.astype(jnp.int32),
                    jnp.minimum(offs // bs_blk, m_tbl - 1),
                    axis=1,
                )  # [B, t]
                off = offs % bs_blk
                k_w = k.astype(self.dtype)  # [B, t, H, hd]
                v_w = v.astype(self.dtype)
                if quant:
                    from frl_distributed_ml_scaffold_tpu.ops.quantization import (
                        quantize,
                    )

                    # Quantize ONCE per written token over its own head
                    # vector (the PR 6 contract): per-(row, pos, head)
                    # scales over hd, identical to the contiguous path's
                    # scale at the same position.
                    qk, sk = quantize(
                        k_w, cfg.kv_cache_quant, channel_axes=(0, 1, 2)
                    )
                    qv, sv = quantize(
                        v_w, cfg.kv_cache_quant, channel_axes=(0, 1, 2)
                    )
                    k_w, v_w = qk, qv
                    ksc.value = _constrain_kv_pool(
                        ksc.value.at[phys, off].set(
                            sk[..., 0].astype(ksc.value.dtype)
                        )
                    )
                    vsc.value = _constrain_kv_pool(
                        vsc.value.at[phys, off].set(
                            sv[..., 0].astype(vsc.value.dtype)
                        )
                    )
                ck.value = _constrain_kv_pool(
                    ck.value.at[phys, off].set(k_w)
                )
                cv.value = _constrain_kv_pool(
                    cv.value.at[phys, off].set(v_w)
                )
                if t == 1:
                    from frl_distributed_ml_scaffold_tpu.ops.decode_attention import (
                        paged_decode_attention,
                    )

                    y = paged_decode_attention(
                        q[:, 0], ck.value, cv.value, idx + 1,
                        block_tables,
                        k_scale=ksc.value if quant else None,
                        v_scale=vsc.value if quant else None,
                        impl=cfg.decode_attention,
                    )[:, None]
                else:
                    # Speculative VERIFY tile (ISSUE 11): all t = k+1
                    # positions score against the paged cache in ONE
                    # forward — causal inside the tile (query j attends
                    # logical positions <= idx + j), so query 0 computes
                    # exactly the single-token decode step's output and
                    # greedy acceptance against these logits is exact.
                    from frl_distributed_ml_scaffold_tpu.ops.decode_attention import (
                        paged_verify_attention,
                    )

                    y = paged_verify_attention(
                        q, ck.value, cv.value, idx + t, block_tables,
                        k_scale=ksc.value if quant else None,
                        v_scale=vsc.value if quant else None,
                        impl=cfg.decode_attention,
                    )
                ci.value = idx + t
                y = y.reshape(b, t, d)
                y = nn.Dense(
                    d, dtype=self.dtype, name="out", dot_general=out_dg
                )(y)
                y = nn.Dropout(cfg.dropout, deterministic=not train)(y)
                return y
            # Cache vars are created lazily on first use: flax permits
            # variable creation during apply when the collection is mutable.
            ck = self.variable(
                "cache", "cached_key", jnp.zeros, (b, s, h, hd), cache_dtype
            )
            cv = self.variable(
                "cache", "cached_value", jnp.zeros, (b, s, h, hd), cache_dtype
            )
            if quant:
                ksc = self.variable(
                    "cache", "key_scale", jnp.zeros, (b, s, h), jnp.bfloat16
                )
                vsc = self.variable(
                    "cache", "value_scale", jnp.zeros, (b, s, h), jnp.bfloat16
                )
            # Per-ROW write index: serving slots decode at different
            # occupancies (continuous batching), so the index is [B], the
            # write is a batched scatter, and the mask is per-row.
            ci = self.variable(
                "cache", "cache_index", jnp.zeros, (b,), jnp.int32
            )
            idx = ci.value  # [B]
            lens = (
                jnp.full((b,), t, jnp.int32)
                if lengths is None
                else lengths.astype(jnp.int32)
            )
            pad = t - lens  # [B] left-pad widths (0 when not ragged)
            k_w, v_w = k.astype(self.dtype), v.astype(self.dtype)
            if t > 1:
                # Ragged prefill: prompts arrive LEFT-padded ([pad | real]
                # columns). Roll each row so its real tokens land at cache
                # slots [0, len) — the cache is stored densely by absolute
                # position, which is what lets the decode kernel read only
                # the occupied prefix. The trailing t-len written slots
                # hold wrapped pad garbage; they sit at positions >= len,
                # masked now and overwritten by later decode steps.
                roll_cols = (jnp.arange(t)[None, :] + pad[:, None]) % t
                k_w = jnp.take_along_axis(
                    k_w, roll_cols[:, :, None, None], axis=1
                )
                v_w = jnp.take_along_axis(
                    v_w, roll_cols[:, :, None, None], axis=1
                )
            rows = jnp.arange(b)[:, None]
            # Columns past the cache capacity are DROPPED, not clipped:
            # a seeded suffix prefill (serving shared-prefix admission,
            # cache_index starting at the prefix length) can push its
            # trailing wrapped-pad garbage columns past ``s`` — clipping
            # would pile them onto position s-1, clobbering a real
            # token's K/V. The same drop also silences retired serving
            # rows whose index has advanced past capacity.
            write_cols = idx[:, None] + jnp.arange(t)[None, :]
            if quant:
                from frl_distributed_ml_scaffold_tpu.ops.quantization import (
                    dequantize,
                    quantize,
                )

                qk, sk = quantize(k_w, cfg.kv_cache_quant,
                                  channel_axes=(0, 1, 2))
                qv, sv = quantize(v_w, cfg.kv_cache_quant,
                                  channel_axes=(0, 1, 2))
                k_w, v_w = qk, qv  # [B, t, H, hd] 1-byte payloads
                ksc.value = _constrain_kv_cache(
                    ksc.value.at[rows, write_cols].set(
                        sk[..., 0].astype(ksc.value.dtype), mode="drop"
                    )
                )
                vsc.value = _constrain_kv_cache(
                    vsc.value.at[rows, write_cols].set(
                        sv[..., 0].astype(vsc.value.dtype), mode="drop"
                    )
                )
            ck.value = _constrain_kv_cache(
                ck.value.at[rows, write_cols].set(k_w, mode="drop")
            )
            cv.value = _constrain_kv_cache(
                cv.value.at[rows, write_cols].set(v_w, mode="drop")
            )
            if t == 1:
                from frl_distributed_ml_scaffold_tpu.ops.decode_attention import (
                    decode_attention,
                )

                y = decode_attention(
                    q[:, 0], ck.value, cv.value, idx + 1,
                    k_scale=ksc.value if quant else None,
                    v_scale=vsc.value if quant else None,
                    impl=cfg.decode_attention,
                )[:, None]
            else:
                # Query at column j has absolute position idx + j - pad
                # (pad columns clip to 0: their outputs are never read,
                # but the softmax must stay finite).
                qpos = jnp.maximum(
                    idx[:, None] + jnp.arange(t)[None, :] - pad[:, None], 0
                )  # [B, t]
                kpos = jnp.arange(s)
                mask = kpos[None, None, :] <= qpos[:, :, None]  # [B, t, S]
                if quant:
                    # Prefill attends over the dequantized bucket — a
                    # [B, bucket, H, hd] widening is the prefill program's
                    # own working-set class (its score tensor is bigger);
                    # the per-STEP no-wide-cache pin applies to t == 1.
                    k_att = dequantize(
                        ck.value, ksc.value[..., None], self.dtype
                    )
                    v_att = dequantize(
                        cv.value, vsc.value[..., None], self.dtype
                    )
                else:
                    k_att, v_att = ck.value, cv.value
                y = _masked_dense_attention(q, k_att, v_att, mask)
            ci.value = idx + lens
        elif cfg.attention == "ring":
            from frl_distributed_ml_scaffold_tpu.ops.ring_attention import (
                ring_attention,
            )

            y = ring_attention(q, k, v, axis_name="seq", causal=True)
        elif cfg.attention == "ulysses":
            from frl_distributed_ml_scaffold_tpu.ops.ulysses import (
                ulysses_attention,
            )

            y = ulysses_attention(q, k, v, axis_name="seq", causal=True)
        elif cfg.attention == "flash":
            from frl_distributed_ml_scaffold_tpu.ops.flash_attention import (
                flash_attention,
            )

            y = flash_attention(q, k, v, causal=True)
        else:
            from frl_distributed_ml_scaffold_tpu.ops import dense_attention

            # Same op (and the same fp32-softmax numerics) as the trivial-axis
            # path of ring/ulysses — dense vs. sharded attention differ only
            # in communication, never in math.
            y = dense_attention(q, k, v, causal=True)

        y = y.reshape(b, t, d)
        y = nn.Dense(d, dtype=self.dtype, name="out", dot_general=out_dg)(y)
        y = nn.Dropout(cfg.dropout, deterministic=not train)(y)
        return y


class GptMlp(nn.Module):
    config: GPTConfig
    dtype: Any
    tp: Any = None  # collective-matmul hooks (see CausalSelfAttention)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        cfg = self.config
        tp = self.tp
        ag_dg = tp.ag_dot_general if tp is not None else None
        mrs_dg = tp.mrs_dot_general if tp is not None else None
        y = nn.Dense(
            cfg.hidden_dim * cfg.mlp_ratio,
            dtype=self.dtype,
            name="fc_in",
            dot_general=ag_dg,
        )(x)
        y = nn.gelu(y)
        y = nn.Dense(
            cfg.hidden_dim, dtype=self.dtype, name="fc_out", dot_general=mrs_dg
        )(y)
        y = nn.Dropout(cfg.dropout, deterministic=not train)(y)
        return y


class Block(nn.Module):
    config: GPTConfig
    dtype: Any
    train: bool  # static per-trace; bound at GPT.__call__ construction time
    decode: bool = False  # KV-cache incremental decoding
    tp: Any = None  # collective-matmul TP hooks (parallel/tp_overlap.py)
    cache_len: int = 0  # decode cache bucket (0 = config.seq_len)
    kv_block_size: int = 0  # paged decode pool (0 = contiguous cache)
    kv_pool_blocks: int = 0

    @nn.compact
    def __call__(self, carry, _unused):
        # Decode mode threads the per-row prompt lengths through the scan
        # carry (a traced array cannot be a module attribute); they are
        # loop-invariant. Paged decode additionally threads the per-row
        # block tables the same way (every layer reads the same tables;
        # the pools themselves are per-layer cache vars).
        tables = None
        if self.decode and self.kv_block_size > 0:
            x, aux_loss, lengths, tables = carry
        elif self.decode:
            x, aux_loss, lengths = carry
        else:
            (x, aux_loss), lengths = carry, None
        cfg, train, tp = self.config, self.train, self.tp
        y = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.layer_norm_epsilon, name="ln1")(x)
        attn_out = CausalSelfAttention(
            cfg, self.dtype, tp=tp, cache_len=self.cache_len,
            kv_block_size=self.kv_block_size,
            kv_pool_blocks=self.kv_pool_blocks, name="attn"
        )(y, train=train, decode=self.decode, lengths=lengths,
          block_tables=tables)
        # Named for block_remat="save_attn": saving this one [B,T,D] tensor
        # per layer lets the per-block recompute skip the attention sublayer
        # (the quadratic part). A no-op unless a checkpoint policy asks.
        attn_out = checkpoint_name(attn_out, "attn_out")
        x = x + attn_out
        if tp is not None:
            # Keep the residual stream sequence-sharded over the model axis
            # between the reduce-scatter that produced attn_out and the
            # gather ring that will consume ln2's output: the add and the
            # LayerNorms are per-token, so anchoring here keeps the whole
            # inter-matmul segment local.
            x = tp.constrain_stream(x)
        y = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.layer_norm_epsilon, name="ln2")(x)
        if cfg.moe.num_experts > 0:
            from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

            mlp_out, layer_aux = MoEMlp(cfg, self.dtype, name="moe")(y, train=train)
            aux_loss = aux_loss + layer_aux
        else:
            mlp_out = GptMlp(cfg, self.dtype, tp=tp, name="mlp")(y, train=train)
        x = x + mlp_out
        if tp is not None:
            x = tp.constrain_stream(x)
        if self.decode and self.kv_block_size > 0:
            return (x, aux_loss, lengths, tables), None
        if self.decode:
            return (x, aux_loss, lengths), None
        return (x, aux_loss), None


class GPT(nn.Module):
    config: GPTConfig
    policy: Policy
    # Blockwise param-gather apply hook (fsdp_overlap.OverlapHooks —
    # lowered from the declared OverlapSchedule's gather(fsdp,block) rule
    # by parallel/schedule.py's executor): when set, each scanned Block's
    # param slice is explicitly all-gathered inside the scan body
    # (nn.map_variables) and the block is rematted with a policy that
    # refuses to save the gathered full params, so the backward
    # re-gathers (reduce-scatter of grads is the gather's transpose).
    # Attached by the Trainer AFTER partition specs exist; init/decode
    # always run unhooked — the params tree is identical either way.
    param_hooks: Any = None
    # Collective-matmul ring hooks (tp_overlap.TpHooks — lowered from the
    # schedule's gather(model,ring_chunk)/scatter(model) pair, with any
    # declared ``lowp`` riding as a transfer attribute): replaces the
    # four GSPMD TP matmuls per block (QKV, attn-out, fc_in, fc_out)
    # with latency-hiding ppermute rings and keeps the residual stream
    # sequence-sharded over the model axis. Attached by the Trainer like
    # param_hooks; init/decode always run unhooked.
    tp_overlap: Any = None
    # Decode KV-cache capacity (0 = config.seq_len). generate()/the
    # serving engine clone the model with the active bucket so the cache
    # arrays — and everything that reads them — are sized to the request
    # window, not the model's maximum context.
    cache_len: int = 0
    # Paged decode cache (ISSUE 10; engine-set via clone, like cache_len):
    # kv_block_size > 0 stores K/V in a shared pool of kv_pool_blocks
    # fixed-size blocks addressed through a per-row ``block_tables``
    # cache var ([B, ceil(seq_len/block_size)] int32, engine-owned) —
    # single-token decode steps only; prefill stays contiguous and the
    # engine grafts it into the pool block-wise.
    kv_block_size: int = 0
    kv_pool_blocks: int = 0

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        *,
        train: bool = False,
        decode: bool = False,
        return_features: bool = False,
        lengths: jnp.ndarray | None = None,
    ):
        cfg = self.config
        dtype = self.policy.compute_dtype
        b, t = tokens.shape
        if lengths is not None and not decode:
            raise ValueError(
                "lengths (ragged left-padded prompts) is a decode-mode "
                "argument; training/eval batches are dense"
            )
        if decode and self.kv_block_size > 0 and t > 1 and lengths is not None:
            raise NotImplementedError(
                "paged multi-token decode is the dense VERIFY tile "
                "(speculative decoding, ISSUE 11) — ragged lengths do "
                "not apply; prefill stays contiguous and the engine "
                "grafts it block-wise into the pool"
            )

        wte = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_dim,
            dtype=dtype,
            embedding_init=nn.initializers.normal(stddev=0.02),
            name="wte",
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(stddev=0.02), (cfg.seq_len, cfg.hidden_dim)
        )
        if decode:
            # Positions are absolute and PER ROW: offset by how much of
            # each row's cache this call's tokens come after (tracked here
            # so the embedding and the per-layer attention caches advance
            # together; rows diverge under ragged prompts and continuous
            # batching). Left-pad columns clip to position 0 — their
            # embeddings feed garbage lanes that the attention mask and
            # the right-aligned logit read both ignore.
            pos = self.variable(
                "cache", "pos_index", jnp.zeros, (b,), jnp.int32
            )
            # Canonical per-row lengths, computed ONCE for the whole
            # decode trace: the position offsets here and the cache
            # writes/masks in every scanned block (via the scan carry)
            # must advance from the same array.
            lens = (
                jnp.full((b,), t, jnp.int32)
                if lengths is None
                else lengths.astype(jnp.int32)
            )
            pos_ids = jnp.clip(
                pos.value[:, None] + jnp.arange(t)[None, :] - (t - lens)[:, None],
                0,
                cfg.seq_len - 1,
            )  # [B, t]
            pe = jnp.take(wpe, pos_ids, axis=0)  # [B, t, D]
            pos.value = pos.value + lens
        else:
            pe = wpe[:t]
        x = wte(tokens) + pe.astype(dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)

        if decode and cfg.pipeline_stages > 1:
            raise NotImplementedError(
                "KV-cache decoding runs on the plain layer stack (pipeline "
                "parallelism is a training-throughput schedule). "
                "models.generation.generate/beam_search restack pipeline "
                "params automatically (unstack_pipeline_params); only a "
                "direct apply(decode=True) needs pipeline_stages=1"
            )
        if cfg.pipeline_stages > 1:
            # flash/ring/ulysses open their own shard_map regions; the
            # pipeline's stage vmap names its axis (spmd_axis_name="pipe"),
            # so those regions batch over the stage dim and compose — no
            # mode exclusions.
            from frl_distributed_ml_scaffold_tpu.parallel.pipeline import (
                CircularSpmdPipeline,
                SpmdPipeline,
                circular_repeat,
                effective_microbatches,
            )

            v = circular_repeat(cfg)
            cls = CircularSpmdPipeline if v > 1 else SpmdPipeline
            pipe = cls(
                Block,
                (cfg, dtype, train),
                num_layers=cfg.num_layers,
                num_stages=cfg.pipeline_stages,
                num_microbatches=effective_microbatches(cfg),
                stage_remat=cfg.pipeline_stage_remat,
                name="pipeline",
                **({"repeat": v} if v > 1 else {}),
            )
            x, aux_loss = pipe(x, jnp.zeros((), jnp.float32))
        else:
            if decode:
                # Decode keeps its own plain scan: hooks/remat are
                # training-path rewrites and never mix with the caches.
                stack_cls = nn.scan(
                    Block,
                    length=cfg.num_layers,
                    variable_axes={"params": 0, "cache": 0},
                    split_rngs={"params": True, "dropout": True},
                )
            else:
                # Shared with the MPMD per-stage programs (GptStage):
                # blockwise param-gather hook + per-block remat + scan.
                stack_cls = _train_block_stack(
                    cfg, length=cfg.num_layers, hooks=self.param_hooks
                )
            blocks = stack_cls(
                cfg,
                dtype,
                train,
                decode,
                None if decode else self.tp_overlap,
                self.cache_len if decode else 0,
                self.kv_block_size if decode else 0,
                self.kv_pool_blocks if decode else 0,
                name="blocks",
            )
            if decode and self.kv_block_size > 0:
                # Paged decode: the block tables are a MODEL-level cache
                # var (one copy, not per-layer — every layer reads the
                # same row→block mapping), threaded to the scanned blocks
                # through the carry like `lens`. The engine writes them
                # host-side between steps; the model only reads.
                m_blocks = -(-cfg.seq_len // self.kv_block_size)
                tbl = self.variable(
                    "cache", "block_tables", jnp.zeros,
                    (b, m_blocks), jnp.int32,
                )
                carry0 = (x, jnp.zeros((), jnp.float32), lens, tbl.value)
                (x, aux_loss, _, _), _ = blocks(carry0, None)
            elif decode:
                # `lens` from the position block above — one defaulting
                # site for the whole decode trace.
                carry0 = (x, jnp.zeros((), jnp.float32), lens)
                (x, aux_loss, _), _ = blocks(carry0, None)
            else:
                (x, aux_loss), _ = blocks(
                    (x, jnp.zeros((), jnp.float32)), None
                )

        x = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.layer_norm_epsilon, name="ln_f")(x)
        if return_features:
            # Pre-head features for the chunked-vocab LM loss (the weight-
            # tied head lives at params['wte']['embedding']; the loss
            # reproduces wte.attend chunk by chunk so the [B, T, vocab]
            # logits tensor never materializes).
            feats = x.astype(dtype)
            if cfg.moe.num_experts > 0:
                return feats, aux_loss
            return feats
        logits = wte.attend(x.astype(dtype))  # weight-tied LM head
        if cfg.moe.num_experts > 0:
            return logits, aux_loss
        return logits
