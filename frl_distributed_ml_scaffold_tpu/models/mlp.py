"""MLP classifier (BASELINE config 1: the MNIST smoke-test model)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import MLPConfig
from frl_distributed_ml_scaffold_tpu.precision import Policy


class MLP(nn.Module):
    config: MLPConfig
    policy: Policy

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = self.policy.compute_dtype
        x = x.reshape((x.shape[0], -1)).astype(dtype)
        for width in cfg.hidden_sizes:
            x = nn.Dense(width, dtype=dtype)(x)
            x = nn.relu(x)
            if cfg.dropout > 0:
                x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        x = nn.Dense(cfg.num_classes, dtype=dtype)(x)
        return x.astype(self.policy.output_dtype)
