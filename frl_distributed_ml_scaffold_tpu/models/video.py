"""Video-clip classifier (BASELINE config 5: the Ego4D-style recipe).

Tubelet-ViT (ViViT-style): a 3D conv embeds (t, h, w) tubelets of the clip
into tokens — one big MXU matmul, same as the ViT patch conv but with a time
dimension — then a standard pre-LN transformer over the spatio-temporal
token sequence with mean pooling. Reuses the ViT encoder blocks.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import VideoConfig
from frl_distributed_ml_scaffold_tpu.models.vit import EncoderBlock
from frl_distributed_ml_scaffold_tpu.precision import Policy


class VideoClassifier(nn.Module):
    config: VideoConfig
    policy: Policy
    # Collective-matmul ring hooks (tp_overlap.TpHooks, lowered from the
    # declared OverlapSchedule's ring rule by parallel/schedule.py),
    # attached by the Trainer for the loss path only (see
    # vit.EncoderBlock).
    tp_overlap: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = self.policy.compute_dtype
        x = x.astype(dtype)  # (B, T, H, W, C)
        tt, th, tw = cfg.tubelet_size
        x = nn.Conv(
            cfg.hidden_dim,
            kernel_size=(tt, th, tw),
            strides=(tt, th, tw),
            padding="VALID",
            dtype=dtype,
        )(x)  # (B, T', H', W', D)
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.hidden_dim)

        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], cfg.hidden_dim),
        )
        x = x + pos.astype(dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)

        for _ in range(cfg.num_layers):
            x = EncoderBlock(
                num_heads=cfg.num_heads,
                mlp_ratio=cfg.mlp_ratio,
                dropout=cfg.dropout,
                dtype=dtype,
                tp=self.tp_overlap,
            )(x, train=train)

        x = nn.LayerNorm(dtype=jnp.float32)(x)
        x = jnp.mean(x, axis=1)
        x = nn.Dense(cfg.num_classes, dtype=dtype)(x)
        return x.astype(self.policy.output_dtype)
