#!/usr/bin/env python
"""On-chip smoke tier (SURVEY §4): the checks only real hardware can give.

CI runs everything on the simulated CPU mesh; the one check it structurally
cannot perform is "the Pallas kernels Mosaic actually compiles produce the
same numbers as the reference math". This tool runs that plus a short
learn-check on the real chip, one JSONL line per check, everything bounded
(the relay can hang — callers should wrap with `timeout`).

    timeout 900 python tools/tpu_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def emit(check: str, ok: bool, **extra) -> None:
    print(json.dumps({"check": check, "ok": bool(ok), **extra}), flush=True)


def main() -> int:
    import jax

    # The axon sitecustomize pins jax_platforms at the config level, which
    # beats env vars — re-assert JAX_PLATFORMS so e.g. a CPU dry run works.
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)

    t0 = time.time()
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev))
    emit("backend_up", True, device=kind, seconds=round(time.time() - t0, 1))
    if jax.default_backend() != "tpu":
        emit("is_tpu", False, backend=jax.default_backend())
        return 1

    import jax.numpy as jnp
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.ops.flash_attention import flash_attention
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import dense_attention

    failures = 0

    # --- Pallas flash kernel, REAL Mosaic compile, vs dense reference ----
    # Tolerance calibration (measured on v5e, 2026-07-30): the MXU runs
    # "fp32" matmuls as bf16 multi-pass by default, so the kernel's dots
    # carry ~4e-3 relative error even for fp32 inputs. The dense reference
    # is therefore computed at precision='highest' (true fp32 accumulate)
    # and the fp32 tolerance is set to what MXU-grade arithmetic warrants
    # (2e-2) — loose enough for bf16 passes, tight enough that any real
    # kernel bug (masking, off-by-block, softmax rescale) shows as O(1).
    for dtype, tol in ((jnp.float32, 2e-2), (jnp.bfloat16, 3e-2)):
        for causal in (True, False):
            ks = jax.random.split(jax.random.key(0), 3)
            q, k, v = (jax.random.normal(kk, (2, 512, 4, 64), dtype) for kk in ks)
            t0 = time.time()
            out = jax.jit(
                lambda q, k, v: flash_attention(q, k, v, causal=causal)
            )(q, k, v)
            with jax.default_matmul_precision("highest"):
                ref = dense_attention(q, k, v, causal=causal)
            err = float(
                jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
            )
            ok = err < tol
            failures += not ok
            emit(
                f"flash_fwd_{np.dtype(dtype).name}_causal{int(causal)}",
                ok, max_abs_err=err, seconds=round(time.time() - t0, 1),
            )

    # Gradients through the real backward kernels.
    ks = jax.random.split(jax.random.key(7), 3)
    q, k, v = (jax.random.normal(kk, (1, 256, 2, 64), jnp.float32) for kk in ks)

    def loss(att):
        return jax.jit(
            jax.grad(
                lambda q, k, v: (att(q, k, v) * jnp.cos(
                    jnp.arange(q.size, dtype=jnp.float32).reshape(q.shape)
                )).sum(),
                argnums=(0, 1, 2),
            )
        )

    g_flash = loss(lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)
    with jax.default_matmul_precision("highest"):
        g_dense = loss(lambda q, k, v: dense_attention(q, k, v, causal=True))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        err = float(jnp.max(jnp.abs(gf - gd)))
        # Same MXU-arithmetic tolerance story as the forward checks above;
        # measured backward-kernel error on v5e is ~3-5e-3.
        ok = err < 2e-2
        failures += not ok
        emit(f"flash_grad_d{name}", ok, max_abs_err=err)

    # --- short real-chip learn check (BASELINE config 1) -----------------
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["data.global_batch_size=256", "data.prefetch=0",
         "trainer.log_every=1000000", "checkpoint.enabled=false",
         "workdir=/tmp/frl_tpu_smoke"],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = trainer.pipeline.global_batch(0)
    losses = []
    for step in range(30):
        state, metrics = trainer.train_step(state, batch)
        if step % 10 == 0 or step == 29:
            losses.append(float(jax.device_get(metrics["loss"])))
    ok = losses[-1] < losses[0] and np.isfinite(losses).all()
    failures += not ok
    emit("mnist_learns_on_chip", ok, losses=[round(l, 4) for l in losses])

    # --- fused AdamW Pallas kernel, REAL Mosaic compile, vs optax -------
    import optax

    from frl_distributed_ml_scaffold_tpu.ops.fused_adamw import fused_adamw

    t0 = time.time()
    params = {"w": jax.random.normal(jax.random.key(3), (1024, 128))}
    grads = jax.tree.map(lambda p: jnp.cos(p), params)
    kw = dict(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    tx_f = fused_adamw(1e-3, **kw)
    tx_r = optax.adamw(1e-3, **kw)
    p_f, s_f = jax.jit(tx_f.fused_apply)(grads, tx_f.init(params), params)
    u_r, _ = tx_r.update(grads, tx_r.init(params), params)
    p_r = optax.apply_updates(params, u_r)
    err = float(jnp.max(jnp.abs(p_f["w"] - p_r["w"])))
    ok = err < 1e-5 and int(jax.device_get(s_f.count)) == 1
    failures += not ok
    emit("fused_adamw_kernel", ok, max_abs_err=err,
         seconds=round(time.time() - t0, 1))

    # --- optimizer-state host offload (pinned_host is TPU-only) ----------
    cfg = apply_overrides(
        get_config("mnist_mlp"),
        ["data.global_batch_size=256", "data.prefetch=0",
         "trainer.log_every=1000000", "checkpoint.enabled=false",
         "trainer.offload_opt_state=true", "workdir=/tmp/frl_tpu_smoke"],
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    kinds = sorted(
        {l.sharding.memory_kind for l in jax.tree.leaves(state.opt_state)}
    )
    batch = trainer.pipeline.global_batch(0)
    l0 = None
    for step in range(20):
        state, metrics = trainer.train_step(state, batch)
        if step == 0:
            l0 = float(jax.device_get(metrics["loss"]))
    l_last = float(jax.device_get(metrics["loss"]))
    ok = kinds == ["pinned_host"] and l_last < l0
    failures += not ok
    emit("opt_state_offload_on_chip", ok, memory_kinds=kinds,
         loss0=round(l0, 4), loss_last=round(l_last, 4))

    # --- MoE sort-vs-einsum dispatch on real Mosaic (round 5) -----------
    # CI pins exact equivalence on the CPU sim; the chip check is that
    # the scatter/gather formulation COMPILES for TPU and agrees there
    # too (gather/scatter lowering differs materially from CPU).
    import dataclasses as _dc

    from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig, MoEConfig
    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    t0 = time.time()
    gcfg = GPTConfig(
        hidden_dim=128, num_heads=4, seq_len=64,
        moe=MoEConfig(num_experts=8, top_k=2, num_groups=1),
    )
    x = jax.random.normal(jax.random.key(0), (4, 64, 128), jnp.float32)
    outs = {}
    # Highest matmul precision: the einsum path's exchange runs on the
    # MXU while sort's gathers are exact, so default-precision error
    # (~4e-3 relative — see the flash calibration above) would not
    # cancel between the two paths and could false-fail the check.
    with jax.default_matmul_precision("highest"):
        for dispatch in ("einsum", "sort"):
            m = MoEMlp(
                _dc.replace(
                    gcfg, moe=_dc.replace(gcfg.moe, dispatch=dispatch)
                ),
                jnp.float32,
            )
            variables = jax.jit(
                lambda v, _m=m: _m.init(jax.random.key(1), v, train=True)
            )(x)
            outs[dispatch] = jax.jit(
                lambda v, xx, _m=m: _m.apply(v, xx, train=True)
            )(variables, x)
    err = float(
        jnp.max(jnp.abs(outs["einsum"][0] - outs["sort"][0]))
    )
    ok = err < 1e-4
    failures += not ok
    emit("moe_sort_dispatch_on_chip", ok, max_abs_err=err,
         seconds=round(time.time() - t0, 1))

    emit("summary", failures == 0, failures=failures)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
