#!/usr/bin/env python
"""Offline compressed-video → clip-shard producer for the video loader (C16).

The Ego4D-analogue training path reads pre-decoded fixed-shape clip shards
(``{split}_clips_XXX.npy (N,T,H,W,C)`` + labels — data/video.py) because
per-step container decode on the host would starve the chip (SURVEY §7
hard part 5). This is the producer half for real compressed footage,
mirroring tools/decode_imagenet.py: decode OFFLINE with TensorFlow's C++
image decoders (IO-only tooling — tf never touches the training path),
then shard.

Supported raw layouts (both the standard frame-extracted convention and
the one compressed container tf can decode without ffmpeg):

    <raw_dir>/<split>/<class>/<video_id>/*.jpg    frame-JPEG directories
    <raw_dir>/<split>/<class>/<video>.gif         animated GIF containers

MP4/AVI need an ffmpeg/decord stack this zero-egress image doesn't ship;
extract frames with ``ffmpeg -i v.mp4 v/frame_%05d.jpg`` wherever ffmpeg
lives, then point this tool at the frame tree — that is the standard
Ego4D preprocessing shape anyway.

    python tools/decode_video.py <raw_dir> <out_dir> --split train \
        [--frames 8] [--frame-stride 1] [--clip-stride 0(=frames)] \
        [--size 64] [--shard-items 256] [--dtype uint8|float32] [--limit N]

Each video yields every full window of ``frames`` frames (temporal
subsample ``--frame-stride``, window hop ``--clip-stride``); frames are
short-side resized and center-cropped to ``size x size``. Labels are the
sorted class-directory order. ``--dtype uint8`` stores 0-255 at 1/4 the
disk; the shared shard gather (data/shards.py → native.gather_rows)
rescales to [0,1] float32, so stored dtype never changes training
statistics — same contract as the ImageNet producer.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # repo root: the sealed-save helper lives in the package

_FRAME_EXTS = (".jpeg", ".jpg", ".png", ".bmp")


def _frame_paths(video_dir: str) -> list[str]:
    return sorted(
        p
        for p in glob.glob(os.path.join(video_dir, "*"))
        if os.path.isfile(p) and p.lower().endswith(_FRAME_EXTS)
    )


def _resize_center_crop(frames, size: int):
    """(T, H, W, 3) uint8/float -> (T, size, size, 3) float32 [0,1] via
    tf's antialiased resize — one call for the whole clip."""
    import tensorflow as tf

    t = tf.convert_to_tensor(frames)
    h, w = t.shape[1], t.shape[2]
    short = min(h, w)
    scale = size / short
    nh, nw = int(np.ceil(h * scale)), int(np.ceil(w * scale))
    t = tf.image.resize(tf.cast(t, tf.float32), (nh, nw), antialias=True)
    top, left = (nh - size) // 2, (nw - size) // 2
    t = t[:, top : top + size, left : left + size, :]
    return np.clip(t.numpy() / 255.0, 0.0, 1.0).astype(np.float32)


def iter_videos(split_dir: str, classes: list[str]):
    """Yield (label, list-of-frame-arrays-or-paths) per video, in sorted
    order. Frame dirs yield path lists (decoded lazily per frame); GIFs
    decode in one shot."""
    import tensorflow as tf

    for label, cls in enumerate(classes):
        cdir = os.path.join(split_dir, cls)
        for entry in sorted(glob.glob(os.path.join(cdir, "*"))):
            if os.path.isdir(entry):
                paths = _frame_paths(entry)
                if paths:
                    yield label, entry, paths
            elif entry.lower().endswith(".gif"):
                try:
                    gif = tf.io.decode_image(
                        tf.io.read_file(entry), expand_animations=True
                    ).numpy()  # (T, H, W, C)
                except Exception as e:  # undecodable: skip, don't crash
                    print(f"skipping {entry}: {e}", file=sys.stderr)
                    continue
                if gif.ndim == 4 and gif.shape[0] >= 1:
                    if gif.shape[-1] == 1:
                        gif = np.repeat(gif, 3, axis=-1)
                    yield label, entry, gif[..., :3]


def decode_frames(paths_or_array):
    """Frame path list -> (T, H, W, 3) uint8; arrays pass through."""
    if isinstance(paths_or_array, np.ndarray):
        return paths_or_array
    import tensorflow as tf

    frames = []
    for p in paths_or_array:
        try:
            img = tf.io.decode_image(
                tf.io.read_file(p), channels=3, expand_animations=False
            ).numpy()
        except Exception as e:
            print(f"skipping frame {p}: {e}", file=sys.stderr)
            continue
        frames.append(img)
    if not frames:
        return np.zeros((0, 1, 1, 3), np.uint8)
    # A real frame dump has one resolution per video; enforce rather than
    # silently stack-fail hours in.
    shapes = {f.shape for f in frames}
    if len(shapes) > 1:
        print(
            f"skipping video with mixed frame shapes {shapes}",
            file=sys.stderr,
        )
        return np.zeros((0, 1, 1, 3), np.uint8)
    return np.stack(frames)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("raw_dir", help="root holding <split>/<class>/<video>")
    ap.add_argument("out_dir")
    ap.add_argument("--split", default="train")
    ap.add_argument("--frames", type=int, default=8,
                    help="frames per stored clip (data.num_frames)")
    ap.add_argument("--frame-stride", type=int, default=1,
                    help="temporal subsampling within a window")
    ap.add_argument("--clip-stride", type=int, default=0,
                    help="window hop in source frames (0 = frames * "
                         "frame_stride: non-overlapping)")
    ap.add_argument("--size", type=int, default=64,
                    help="stored side; must equal data.image_size")
    ap.add_argument("--shard-items", type=int, default=256)
    ap.add_argument("--dtype", default="uint8", choices=["uint8", "float32"])
    ap.add_argument("--limit", type=int, default=0,
                    help="stop after N clips (0 = all; for smoke runs)")
    ap.add_argument("--splits", default="",
                    help="comma-separated split dirs whose class lists are "
                         "unioned for label ids (default: every "
                         "subdirectory of raw_dir); pin this when raw_dir "
                         "holds non-split directories")
    args = ap.parse_args()

    from frl_distributed_ml_scaffold_tpu.data.shards import (
        derive_label_classes,
    )

    split_dir = os.path.join(args.raw_dir, args.split)
    # Label ids must agree ACROSS splits — union class list over the
    # split set + cross-check against any earlier split's meta (one
    # implementation for both producers: data/shards.py).
    try:
        classes, split_names = derive_label_classes(
            args.raw_dir, args.split, args.splits, args.out_dir
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    span = args.frames * args.frame_stride
    hop = args.clip_stride or span
    os.makedirs(args.out_dir, exist_ok=True)
    buf_x, buf_y, shard_idx, written, videos = [], [], 0, 0, 0

    def flush():
        nonlocal buf_x, buf_y, shard_idx
        if not buf_x:
            return
        from frl_distributed_ml_scaffold_tpu.data.shards import sealed_save

        # Sealed (tmp+rename) writes, DATA before LABELS — the streaming
        # tier's pair-commit contract (data/streaming.py).
        sealed_save(
            os.path.join(
                args.out_dir, f"{args.split}_clips_{shard_idx:03d}.npy"
            ),
            np.stack(buf_x),
        )
        sealed_save(
            os.path.join(
                args.out_dir, f"{args.split}_labels_{shard_idx:03d}.npy"
            ),
            np.asarray(buf_y, np.int32),
        )
        shard_idx += 1
        buf_x, buf_y = [], []

    done = False
    for label, name, frames_src in iter_videos(split_dir, classes):
        if done:
            break
        raw = decode_frames(frames_src)
        if len(raw) < span:
            print(
                f"skipping {name}: {len(raw)} frames < window {span}",
                file=sys.stderr,
            )
            continue
        videos += 1
        clip_stack = _resize_center_crop(raw, args.size)
        for start in range(0, len(clip_stack) - span + 1, hop):
            clip = clip_stack[start : start + span : args.frame_stride]
            if args.dtype == "uint8":
                clip = np.round(clip * 255.0).astype(np.uint8)
            buf_x.append(clip)
            buf_y.append(label)
            written += 1
            if len(buf_x) >= args.shard_items:
                flush()
            if args.limit and written >= args.limit:
                done = True
                break
    flush()
    meta = {
        "split": args.split, "clips": written, "videos": videos,
        "classes": len(classes), "frames": args.frames,
        "frame_stride": args.frame_stride, "clip_stride": hop,
        "size": args.size, "dtype": args.dtype, "shards": shard_idx,
        "class_names": classes, "label_splits": split_names,
    }
    with open(
        os.path.join(args.out_dir, f"{args.split}_meta.json"), "w"
    ) as fh:
        json.dump(meta, fh, indent=2)
    print(json.dumps(meta))
    return 0 if written else 3


if __name__ == "__main__":
    raise SystemExit(main())
