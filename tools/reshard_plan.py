#!/usr/bin/env python
"""reshard_plan CLI: price a mesh-to-mesh redistribution WITHOUT running it.

Compiles the redistribution plan (ISSUE 15, redistribute/plan.py) for a
named seam on the CPU sim and prints the per-leaf program + cost table —
kind (identity / collective / chunked / host), bytes moved vs the
shard-delta lower bound, and the peak scratch transient — the dry-run an
operator reads before a live migration (docs/operations.md "State
redistribution").

    python tools/reshard_plan.py --seam train_to_serve --dry-run
    python tools/reshard_plan.py --seam restore --dry-run
    python tools/reshard_plan.py --seam respread --from-model 2 --to-model 4
    python tools/reshard_plan.py --seam train_to_serve --json plan.json

Seams (all tiny-GPT twins, the graft-lint shrink-shape discipline):

- ``train_to_serve``: fsdp×model training layout → serving TP mesh
  (the ``build_engine(rules=...)`` handoff);
- ``restore``: the even restore layout → fsdp target shardings on one
  mesh (what ``checkpoint.restore_redistribute=true`` executes);
- ``respread``: a paged KV pool re-spread across model-axis sizes
  (``ServingEngine.respread_pool``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Platform pins BEFORE jax imports (the graft_lint.py discipline).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _twin():
    # The SHARED shrink-shape twin (analysis.runner.build_tiny_gpt) —
    # one definition for the ledger row and all three CLI seams.
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        build_tiny_gpt,
    )

    return build_tiny_gpt()


def _with_shardings(tree, shardings):
    import jax

    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        tree, shardings,
    )


def plan_train_to_serve():
    # The SHARED tiny-GPT abstract twin (analysis.runner) — the same
    # plan the perf-ledger redistribute:train_to_serve row gates, so
    # the dry-run an operator reads and the gated numbers cannot drift.
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        build_train_to_serve_plan,
    )

    plan, _train_env, _serve_env = build_train_to_serve_plan()
    return plan


def plan_restore():
    from jax.sharding import NamedSharding

    import jax

    from frl_distributed_ml_scaffold_tpu import redistribute
    from frl_distributed_ml_scaffold_tpu.config.schema import ParallelConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        MeshConfig,
        build_mesh,
    )
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        param_specs,
        shardings_from_specs,
    )

    _model, params = _twin()
    env = build_mesh(MeshConfig(data=2, fsdp=4))
    specs = param_specs(
        params,
        ParallelConfig(param_sharding="fsdp", fsdp_min_size=16),
        env.mesh,
        None,
    )
    target = shardings_from_specs(specs, env.mesh)
    even = jax.tree.map(
        lambda s, sh: NamedSharding(
            sh.mesh,
            redistribute.restore_layout_spec(s.shape, sh.spec, sh.mesh),
        ),
        params, target,
    )
    return redistribute.compile_tree_plan(
        _with_shardings(params, even), target
    )


def plan_respread(from_model: int, to_model: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from frl_distributed_ml_scaffold_tpu import redistribute
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        MeshConfig,
        build_mesh,
    )
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        pool_leaf_spec,
    )

    base, params = _twin()
    model = base.clone(kv_block_size=8, kv_pool_blocks=9)
    tok = jax.ShapeDtypeStruct((2, 1), jnp.int32)
    cache = jax.eval_shape(
        lambda p, t: model.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        )[1]["cache"],
        params, tok,
    )
    src_env = build_mesh(
        MeshConfig(data=1, model=from_model),
        devices=jax.devices()[:from_model],
    )
    dst_env = build_mesh(
        MeshConfig(data=1, model=to_model),
        devices=jax.devices()[:to_model],
    )
    from flax.traverse_util import flatten_dict, unflatten_dict

    def shard_tree(env):
        out = {}
        for kp, leaf in flatten_dict(cache).items():
            spec = pool_leaf_spec(kp[-1], leaf) or P()
            out[kp] = redistribute.spec_on(env.mesh, leaf, spec)
        return unflatten_dict(out)

    src = _with_shardings(cache, shard_tree(src_env))
    return redistribute.compile_tree_plan(src, shard_tree(dst_env))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--seam", required=True,
        choices=("train_to_serve", "restore", "respread"),
    )
    ap.add_argument(
        "--dry-run", action="store_true",
        help="compile + print only (the default and ONLY mode: this "
        "tool never moves data)",
    )
    ap.add_argument("--from-model", type=int, default=2,
                    help="respread: source model-axis size")
    ap.add_argument("--to-model", type=int, default=4,
                    help="respread: destination model-axis size")
    ap.add_argument("--json", help="write the plan table as JSON here")
    args = ap.parse_args(argv)

    if args.seam == "train_to_serve":
        plan = plan_train_to_serve()
    elif args.seam == "restore":
        plan = plan_restore()
    else:
        plan = plan_respread(args.from_model, args.to_model)

    d = plan.to_dict()
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(d, fh, indent=1)
        print(f"wrote plan to {args.json}")
    width = max(len(l["path"]) for l in d["leaves"])
    print(f"seam: {args.seam}")
    print(
        f"  {'leaf':<{width}s} {'kind':<10s} {'src':<28s} {'dst':<28s} "
        f"{'bytes':>9s} {'moved':>9s} {'floor':>9s} {'scratch':>9s}"
    )
    for l in d["leaves"]:
        print(
            f"  {l['path']:<{width}s} {l['kind']:<10s} "
            f"{l['src'][:27]:<28s} {l['dst'][:27]:<28s} "
            f"{l['leaf_bytes']:>9d} {l['bytes_moved']:>9d} "
            f"{l['bytes_lower_bound']:>9d} {l['peak_scratch_bytes']:>9d}"
        )
    for line in plan.summary_lines():
        print(line)
    if d["bytes_moved"] > d["bytes_lower_bound"]:
        print(
            f"  note: plan moves {d['bytes_moved'] - d['bytes_lower_bound']}"
            " bytes over the shard-delta floor"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
