#!/usr/bin/env python
"""Single-chip perf bisection for the RN50/ViT-B headline configs.

Run on a live TPU to localize where step time goes before optimizing
(BASELINE.md backlog). Each experiment is one JSONL line to stdout;
timing uses device_get of the loss (the axon relay's block_until_ready
reports donated buffers ready immediately — see utils/timing.py).

    python tools/perf_sweep.py            # full sweep
    python tools/perf_sweep.py rn50_bs    # one experiment group
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")


def timed_steps(trainer, state, batch, n=12, warm=3):
    """Returns (per-step seconds, final state). The final state matters:
    train_step donates its input state, so callers must NEVER reuse the
    state they passed in (deleted buffers on real TPU)."""
    import jax

    for _ in range(warm):
        state, m = trainer.train_step(state, batch)
    jax.device_get(m["loss"])
    t0 = time.perf_counter()
    for _ in range(n):
        state, m = trainer.train_step(state, batch)
    jax.device_get(m["loss"])
    return (time.perf_counter() - t0) / n, state


def build(name, overrides):
    import gc

    import jax

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    # Release the previous combo's buffers BEFORE allocating this one's:
    # sweeping big configs in one process otherwise accumulates the old
    # trainer's params/opt-state/executables (reference cycles defer GC;
    # the jit cache pins executables) until HBM-heavy combos that fit in
    # isolation die with RESOURCE_EXHAUSTED — exactly what the first
    # on-chip run of gpt2_opt produced (evidence_r4/perf_sweep.log:
    # 17/18 combos failed after combo 1 succeeded).
    gc.collect()
    jax.clear_caches()
    gc.collect()
    cfg = apply_overrides(
        get_config(name),
        ["data.prefetch=0", "trainer.log_every=1000000"] + overrides,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    batch = trainer.pipeline.global_batch(0)
    return trainer, state, batch


def emit(tag, bs, dt, extra=None):
    rec = {
        "experiment": tag,
        "global_batch_size": bs,
        "step_time_ms": round(dt * 1e3, 2),
        "samples_per_sec_per_chip": round(bs / dt, 1),
    }
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)



def measure(name, overrides, n=12, warm=3):
    """Build -> time -> release. Holds no refs to the previous combo while
    the next one allocates (build() collects the garbage); use this for
    every multi-combo sweep over HBM-heavy configs."""
    t, s, b = build(name, overrides)
    dt, s = timed_steps(t, s, b, n=n, warm=warm)
    del t, s, b
    return dt


def measure_or_emit(experiment, bs, name, overrides, tag, *, n=12, warm=3):
    """measure() + emit(), recording failures as rows instead of aborting
    the sweep — HBM-rejected combos are DATA (they map the memory wall).
    One implementation for every grid that wants keep-sweeping semantics.
    """
    try:
        dt = measure(name, overrides, n=n, warm=warm)
        emit(experiment, bs, dt, tag)
    except Exception as e:
        print(
            json.dumps(
                {"experiment": experiment, "global_batch_size": bs,
                 **tag, "error": str(e)[:160]}
            ),
            flush=True,
        )

def rn50_bs():
    """Throughput knee: where does adding batch stop helping?"""
    for bs in (256, 512, 768, 1024):
        dt = measure("imagenet_rn50_ddp", [f"data.global_batch_size={bs}"])
        emit("rn50_bs", bs, dt)


def rn50_precision():
    for policy in ("bf16_mixed", "bf16", "fp32"):
        dt = measure(
            "imagenet_rn50_ddp",
            ["data.global_batch_size=512", f"precision.policy={policy}"],
        )
        emit("rn50_precision", 512, dt, {"policy": policy})


def rn50_fwd_only():
    """Eval step ~= forward: splits fwd from bwd+update in the step time."""
    import jax

    t, s, b = build("imagenet_rn50_ddp", ["data.global_batch_size=512"])
    dt, s = timed_steps(t, s, b)  # s was donated; use the returned state
    emit("rn50_train", 512, dt)
    for _ in range(3):
        m = t.eval_step(s, b)
    jax.device_get(m["loss"])
    t0 = time.perf_counter()
    for _ in range(10):
        m = t.eval_step(s, b)
    jax.device_get(m["loss"])
    emit("rn50_eval_fwd", 512, (time.perf_counter() - t0) / 10)


def rn50_depth():
    """Stem vs body: depth-18 shares the stem; scaling with depth separates
    the (fixed) stem+head cost from the residual body."""
    for depth in (18, 34, 50):
        dt = measure(
            "imagenet_rn50_ddp",
            ["data.global_batch_size=512", f"model.depth={depth}"],
        )
        emit("rn50_depth", 512, dt, {"depth": depth})


def rn50_stem():
    """conv7 vs the exact space-to-depth rewrite (MLPerf stem)."""
    for stem in ("conv7", "s2d"):
        dt = measure(
            "imagenet_rn50_ddp",
            ["data.global_batch_size=512", f"model.stem={stem}"],
        )
        emit("rn50_stem", 512, dt, {"stem": stem})


def rn50_split():
    """Where does the 228ms step go? fwd+loss (train-mode BN) vs fwd+bwd
    (grad, no update) vs the full step — separates forward, backward and
    optimizer/update costs with the real training-mode graph."""
    import jax
    import jax.numpy as jnp

    t, s, b = build("imagenet_rn50_ddp", ["data.global_batch_size=512"])
    dt, s = timed_steps(t, s, b)
    emit("rn50_split_full_step", 512, dt)

    lf = t.loss_fn
    rng = jax.random.key(0)

    fwd = jax.jit(lambda st, bt: lf(st.params, st.extras, bt, rng, True)[0])
    for _ in range(3):
        l = fwd(s, b)
    jax.device_get(l)
    t0 = time.perf_counter()
    for _ in range(10):
        l = fwd(s, b)
    jax.device_get(l)
    emit("rn50_split_fwd_train", 512, (time.perf_counter() - t0) / 10)

    grad = jax.jit(
        lambda st, bt: jax.grad(
            lambda p: lf(p, st.extras, bt, rng, True)[0]
        )(st.params)
    )

    def gnorm(g):
        return jnp.sqrt(sum(jnp.vdot(x, x) for x in jax.tree.leaves(g)))

    for _ in range(3):
        g = grad(s, b)
    jax.device_get(gnorm(g))
    t0 = time.perf_counter()
    for _ in range(10):
        g = grad(s, b)
    jax.device_get(gnorm(g))
    emit("rn50_split_fwd_bwd", 512, (time.perf_counter() - t0) / 10)


def vitb():
    for bs in (128, 256, 512):
        dt = measure("imagenet_vitb_fsdp", [f"data.global_batch_size={bs}"])
        emit("vitb_bs", bs, dt)


def rn50_headline():
    """Exactly the bench.py headline candidate (s2d stem, bs=512), timed
    with a long window so XLA-flag experiments (tools/xla_flag_sweep.py —
    flags must be set before jax init, hence one subprocess per flag set)
    compare step time, not relay sync RTT."""
    import os

    t, s, b = build(
        "imagenet_rn50_ddp",
        ["data.global_batch_size=512", "model.stem=s2d"],
    )
    dt, _ = timed_steps(t, s, b, n=30, warm=4)
    emit("rn50_headline", 512, dt,
         {"xla_flags": os.environ.get("XLA_FLAGS", "")})


def rn50_pool():
    """select_and_scatter vs the mask-based custom-VJP maxpool backward
    (models/resnet.py::_max_pool_mask_grad) on the headline candidate."""
    for pg in ("scatter", "mask"):
        dt = measure(
            "imagenet_rn50_ddp",
            ["data.global_batch_size=512", "model.stem=s2d",
             f"model.pool_grad={pg}"],
            n=30, warm=4,
        )
        emit("rn50_pool", 512, dt, {"pool_grad": pg})


def gpt2_opt():
    """Attack the worst headline number (GPT-2-medium 33.7% MFU, VERDICT r2):
    the binding constraint is AdamW's ~4.3 GB fp32 state, and the repo
    already ships two state-lean optimizers — Adafactor (sublinear) and
    Lion (half). Sweep optimizer x microbatch x remat; HBM-rejected combos
    are recorded as rows (the relay rejects at compile), so the output maps
    the memory wall, not just the throughput."""
    base = [
        "model.attention=flash",
        "model.lm_loss_chunk=128",
        "trainer.grad_accum=1",
    ]
    for opt in ("adamw", "adafactor", "lion"):
        for mb in (4, 8, 16):
            for remat in ("dots", "none"):
                measure_or_emit(
                    "gpt2_opt", mb, "gpt2_medium_zero1",
                    base + [
                        f"optimizer.name={opt}",
                        f"data.global_batch_size={mb}",
                        f"trainer.remat={remat}",
                    ],
                    {"optimizer": opt, "remat": remat},
                    n=10, warm=3,
                )


def gpt2_block_remat():
    """The round-4 attack on the 33.7% MFU wall: per-block remat
    (model.block_remat) caps backward residency at the L carry boundaries
    plus one block's internals — the flagship audit (pp_memory_audit
    --flagship) shows mb8 needs 6.8G (full) / 7.2G (save_attn) vs 24.5G
    with remat=dots — so the microbatch can finally grow past 4. Sweep
    the unlocked operating points against the mb4/dots protocol line."""
    base = [
        "model.attention=flash",
        "model.lm_loss_chunk=128",
        "trainer.grad_accum=1",
        "trainer.remat=none",
    ]
    # Protocol baseline first so every run of this group is self-contained.
    dt = measure(
        "gpt2_medium_zero1",
        ["model.attention=flash", "model.lm_loss_chunk=128",
         "trainer.grad_accum=1", "data.global_batch_size=4",
         "trainer.remat=dots"],
        n=10, warm=3,
    )
    emit("gpt2_block_remat", 4, dt, {"remat": "dots", "block_remat": "none"})
    for br in ("save_attn", "full"):
        for mb in (8, 16, 32):
            measure_or_emit(
                "gpt2_block_remat", mb, "gpt2_medium_zero1",
                base + [
                    f"model.block_remat={br}",
                    f"data.global_batch_size={mb}",
                ],
                {"remat": "none", "block_remat": br},
                n=10, warm=3,
            )


def gpt2_fsdp_overlap():
    """Round-6 A/B, queued for the next multi-chip relay window (BACKLOG):
    overlap-scheduled FSDP (parallel.fsdp_overlap — explicit per-block
    all-gather/reduce-scatter with one-block-ahead prefetch) vs the plain
    GSPMD FSDP schedule, at the flagship operating point of the
    gpt2_medium_fsdp_overlap recipe. Needs >= 2 devices for a real fsdp
    axis; on the single-chip relay it emits a skip row instead of a
    meaningless comm-free "A/B". Correctness is already sim-gated
    (tests/test_fsdp_overlap.py); this measures whether the explicit
    schedule recovers the hidden gather time (docs/perf_playbook.md
    "Overlap-scheduled FSDP")."""
    import jax

    n = jax.device_count()
    if n < 2:
        print(json.dumps({
            "experiment": "gpt2_fsdp_overlap",
            "skipped": f"needs >=2 devices for an fsdp axis (have {n})",
        }), flush=True)
        return
    base = [
        "model.attention=flash",
        "model.lm_loss_chunk=128",
        "trainer.grad_accum=1",
        "trainer.remat=none",
        "model.block_remat=full",
        "mesh.data=1",
        f"mesh.fsdp={n}",
    ]
    for overlap in ("false", "true"):
        for per_chip in (8, 16):
            bs = per_chip * n
            measure_or_emit(
                "gpt2_fsdp_overlap", bs, "gpt2_medium_fsdp_overlap",
                base + [
                    f"parallel.fsdp_overlap={overlap}",
                    f"data.global_batch_size={bs}",
                ],
                {"fsdp_overlap": overlap, "n_chips": n},
                n=10, warm=3,
            )


def gpt2_tp_overlap():
    """Round-7 A/B, queued for the next multi-chip relay window (BACKLOG
    R7): latency-hiding tensor parallelism (parallel.tp_overlap — the
    collective-matmul ppermute rings of ops/collective_matmul.py) vs the
    plain GSPMD TP schedule, at the gpt2_medium_tp_overlap operating
    point. Needs >= 2 devices for a real model axis; on the single-chip
    relay it emits a skip row instead of a meaningless comm-free "A/B".
    Correctness is already sim-gated (tests/test_tp_overlap.py); this
    measures whether the rings actually hide the per-layer model-axis
    comm — capture a trace alongside and read tools/trace_analyze.py's
    per-class overlap summary (collective-permute hidden vs exposed)."""
    import jax

    n = jax.device_count()
    if n < 2:
        print(json.dumps({
            "experiment": "gpt2_tp_overlap",
            "skipped": f"needs >=2 devices for a model axis (have {n})",
        }), flush=True)
        return
    base = [
        "trainer.grad_accum=1",
        "trainer.remat=none",
        "model.block_remat=full",
        "mesh.data=1",
        f"mesh.model={n}",
    ]
    for overlap in ("false", "true"):
        for global_bs in (8, 16):
            measure_or_emit(
                "gpt2_tp_overlap", global_bs, "gpt2_medium_tp_overlap",
                base + [
                    f"parallel.tp_overlap={overlap}",
                    f"data.global_batch_size={global_bs}",
                ],
                {"tp_overlap": overlap, "n_chips": n},
                n=10, warm=3,
            )


def moe_dispatch():
    """Round-5 A/B the FLOP table predicts sort wins (einsum exchange =
    66% of step FLOPs at the audited shapes; sort cuts total 1.79x —
    tools/moe_dispatch_cost.py / docs/perf_playbook.md "Dispatch
    FLOPs"). Measures the full gpt2_moe single-chip protocol operating
    point under each moe.dispatch; the recipe default flips only if the
    measured step time agrees with the cost model (BACKLOG R5-2)."""
    base = [
        "data.global_batch_size=8", "trainer.grad_accum=1",
        "model.attention=flash", "model.lm_loss_chunk=128",
        "mesh.expert=1", "optimizer.name=adafactor",
        "trainer.remat=none", "model.block_remat=full",
    ]
    for dispatch in ("einsum", "sort"):
        measure_or_emit(
            "moe_dispatch", 8, "gpt2_moe",
            base + [f"model.moe.dispatch={dispatch}"],
            {"dispatch": dispatch}, n=10, warm=3,
        )


def gpt2_offload():
    """Re-test opt-state host offload under bigger batches: the ~17x
    pinned_host streaming cost (docs/perf_playbook.md) amortizes
    differently when the freed HBM buys 2-4x microbatch."""
    base = [
        "model.attention=flash",
        "model.lm_loss_chunk=128",
        "trainer.grad_accum=1",
        "trainer.offload_opt_state=true",
    ]
    for opt in ("adamw", "adafactor"):
        for mb in (8, 16, 32):
            measure_or_emit(
                "gpt2_offload", mb, "gpt2_medium_zero1",
                base + [
                    f"optimizer.name={opt}",
                    f"data.global_batch_size={mb}",
                    "trainer.remat=dots",
                ],
                {"optimizer": opt}, n=8, warm=3,
            )


def rn50_fused_opt():
    """BACKLOG-5 experiment: the RN50 optimizer+casts segment is ~7 ms/step
    of pure bandwidth; compare the recipe default (sgd), optax adamw, and
    the single-Pallas-pass fused_adamw (ops/fused_adamw.py). Ship
    fused_adamw as a recommendation only if this measures a win."""
    for opt in ("sgd", "adamw", "fused_adamw"):
        dt = measure(
            "imagenet_rn50_ddp",
            ["data.global_batch_size=512", "model.stem=s2d",
             f"optimizer.name={opt}"],
            n=30, warm=4,
        )
        emit("rn50_fused_opt", 512, dt, {"optimizer": opt})


def gpt2_fsdp_tp_overlap():
    """The composed-schedule A/B (ISSUE 13, queued for the next
    multi-chip relay window alongside R6-1/R7-1): the unified overlap
    schedule with BOTH axes declared — blockwise fsdp gathers AND
    model-axis collective-matmul rings in one scan body
    (gpt2_medium_fsdp_tp_overlap) — vs the all-GSPMD fsdp x model
    schedule, plus the int8 transfer arm (lowp as a schedule attribute).
    Needs >= 4 devices (a real fsdp axis x model=2); on a smaller relay
    it emits a skip row. Correctness is sim-gated (tests/test_schedule.py
    numerics grid + assert_schedule jaxpr/census pins); this measures
    whether the composed explicit schedules hide BOTH collective classes
    at once — capture a trace and read tools/trace_analyze.py's
    per-class overlap summary (all-gather AND collective-permute hidden
    vs exposed)."""
    import jax

    n = jax.device_count()
    if n < 4:
        print(json.dumps({
            "experiment": "gpt2_fsdp_tp_overlap",
            "skipped": f"needs >=4 devices for fsdp x model (have {n})",
        }), flush=True)
        return
    base = [
        "trainer.grad_accum=1",
        "trainer.remat=none",
        "model.block_remat=full",
        "mesh.data=1",
        f"mesh.fsdp={n // 2}",
        "mesh.model=2",
    ]
    for overlap, lowp in (("false", "none"), ("true", "none"),
                          ("true", "int8")):
        for per_chip in (4, 8):
            bs = per_chip * n
            measure_or_emit(
                "gpt2_fsdp_tp_overlap", bs, "gpt2_medium_fsdp_tp_overlap",
                base + [
                    f"parallel.fsdp_overlap={overlap}",
                    f"parallel.tp_overlap={overlap}",
                    f"parallel.low_precision={lowp}",
                    f"data.global_batch_size={bs}",
                ],
                {"overlap": overlap, "lowp": lowp, "n_chips": n},
                n=10, warm=3,
            )


def gpt2_pipeline_mpmd():
    """The MPMD-vs-SPMD pipeline backend A/B (ISSUE 14, queued as
    BACKLOG R17-1 for the next multi-chip relay window): the gpt2_pp
    operating point (4 stages x 8 microbatches) under the stage-vmap
    GPipe program vs the per-stage-program 1F1B driver
    (model.pipeline_impl) — the step-time delta reads as
    schedule+memory-profile win alone (loss/token parity is sim-gated in
    tests/test_mpmd_pipeline.py). Needs >= 4 devices for the pipe axis;
    capture a trace and check the driver's explicit device_put transfers
    overlap the per-stage compute (trace_analyze lanes), plus HBM
    headroom at larger microbatch counts — 1F1B's min(S, M) live
    activations vs GPipe's M is the lever that buys bigger M (smaller
    bubble) at flat memory."""
    import jax

    n = jax.device_count()
    if n < 4:
        print(json.dumps({
            "experiment": "gpt2_pipeline_mpmd",
            "skipped": f"needs >=4 devices for the pipe axis (have {n})",
        }), flush=True)
        return
    for impl in ("spmd", "mpmd"):
        for micro in (8, 16):
            bs = 64
            measure_or_emit(
                "gpt2_pipeline_mpmd", bs, "gpt2_pipeline_mpmd",
                [
                    f"model.pipeline_impl={impl}",
                    f"model.pipeline_microbatches={micro}",
                    "mesh.pipe=4",
                    f"mesh.data={n // 4}",
                    f"data.global_batch_size={bs}",
                ],
                {"impl": impl, "microbatches": micro, "n_chips": n},
                n=10, warm=3,
            )


def reshard_train_to_serve():
    """The train→serve handoff A/B (ISSUE 15, queued as BACKLOG R18-1):
    redistribute a gpt2 fsdp×model training params tree onto the
    serving TP layout via the plan executor vs the replicated-staging
    reference (device_get the full tree, device_put per the serving
    specs). The sim-gated side pins bit-identity and the scratch budget
    (tests/test_redistribute.py); this measures the wall-clock and
    effective GB/s of both paths on real ICI, where the executor's
    shard-delta transfers should win by roughly the replication factor.
    Needs >= 4 devices (a real fsdp axis x model=2)."""
    import jax
    import numpy as np

    n = jax.device_count()
    if n < 4:
        print(json.dumps({
            "experiment": "reshard_train_to_serve",
            "skipped": f"needs >=4 devices for fsdp x model (have {n})",
        }), flush=True)
        return
    from frl_distributed_ml_scaffold_tpu import redistribute
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        MeshConfig, build_mesh,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import gpt_tp_rules
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        shard_params_for_serving,
    )

    trainer, state, _ = build(
        "gpt2_medium_zero1",
        [f"mesh.fsdp={n // 2}", "mesh.model=2",
         "data.global_batch_size=16", "checkpoint.enabled=false"],
    )
    serve_env = build_mesh(
        MeshConfig(data=1, model=2), devices=jax.devices()[:2]
    )
    for arm in ("redistribute", "replicated_staging"):
        t0 = time.perf_counter()
        if arm == "redistribute":
            placed, plan = redistribute.train_to_serve(
                state.params, serve_env, gpt_tp_rules()
            )
            moved = plan.bytes_moved
        else:
            host = jax.device_get(state.params)  # the staging the
            # executor exists to avoid — measured as the reference
            placed = shard_params_for_serving(host, serve_env, gpt_tp_rules())
            moved = sum(
                np.prod(l.shape) * l.dtype.itemsize
                for l in jax.tree.leaves(host)
            )
        jax.block_until_ready(placed)
        dt = time.perf_counter() - t0
        print(json.dumps({
            "experiment": "reshard_train_to_serve",
            "arm": arm,
            "wall_s": round(dt, 4),
            "bytes_moved": int(moved),
            "gbytes_per_s": round(moved / dt / 1e9, 3),
            "n_chips": n,
        }), flush=True)
        del placed


def rn50_fused_bn():
    """The priced HBM-ceiling fix, bought (BACKLOG R5-4): the roofline
    pins ~150 ms of the 227 ms headline step in BN-backward HBM traffic
    (docs/perf_playbook.md); A/B the fused two-pass Pallas BN backward
    (ops/fused_bn.py, model.fused_bn) against the autodiff reference at
    the exact headline operating point. Long windows: the delta at stake
    is ~15-20% of step time, but per-window noise on the relay is ~1%."""
    for fused in ("false", "true"):
        dt = measure(
            "imagenet_rn50_ddp",
            ["data.global_batch_size=512", "model.stem=s2d",
             f"model.fused_bn={fused}"],
            n=30, warm=4,
        )
        emit("rn50_fused_bn", 512, dt, {"fused_bn": fused})


GROUPS = {f.__name__: f for f in (rn50_bs, rn50_precision, rn50_fwd_only,
                                  rn50_depth, rn50_stem, rn50_split, vitb,
                                  rn50_headline, rn50_pool, gpt2_opt,
                                  gpt2_block_remat, gpt2_offload,
                                  rn50_fused_opt, rn50_fused_bn,
                                  moe_dispatch, gpt2_fsdp_overlap,
                                  gpt2_tp_overlap, gpt2_fsdp_tp_overlap,
                                  gpt2_pipeline_mpmd,
                                  reshard_train_to_serve)}

if __name__ == "__main__":
    which = sys.argv[1:] or list(GROUPS)
    for g in which:
        try:
            GROUPS[g]()
        except Exception as e:  # keep sweeping; record the failure
            print(json.dumps({"experiment": g, "error": str(e)[:200]}),
                  flush=True)
