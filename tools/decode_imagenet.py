#!/usr/bin/env python
"""Offline JPEG → ``.npy`` shard producer for the ImageNet loader (C16).

The training-path loaders read pre-decoded, fixed-shape shards
(``{split}_images_XXX.npy`` + ``{split}_labels_XXX.npy`` — data/shards.py)
because per-step JPEG decode on the host would starve the chip (SURVEY §7
hard part 5). This tool is the missing producer half for a real ImageNet
copy: it walks the standard per-class layout

    <raw_dir>/<split>/<wnid_or_class_name>/*.JPEG

decodes with TensorFlow's C++ JPEG decoder (tf is already in the image —
no new dependency; tf is used for IO only, nothing touches the training
path), resizes the short side to ``--size`` and center-crops to
``size x size``, and writes shards the loader memmaps directly:

    python tools/decode_imagenet.py <raw_dir> <out_dir> --split train \
        [--size 256] [--shard-items 1024] [--dtype uint8|float32] [--limit N]

Labels are the sorted class-directory order (the standard wnid->index
convention). ``--dtype uint8`` stores raw 0-255 pixels at 1/4 the disk of
float32; the loader rescales to [0,1] on gather before the augment kernel
normalizes, so stored dtype never changes training statistics.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def iter_decoded(files, size: int):
    """Yield center-cropped ``size x size x 3`` float32 [0,1] images."""
    import tensorflow as tf  # IO-only; never imported by the training path

    for path in files:
        data = tf.io.read_file(path)
        img = tf.io.decode_image(
            data, channels=3, expand_animations=False
        )  # JPEG/PNG/BMP; uint8 HWC
        h = tf.shape(img)[0]
        w = tf.shape(img)[1]
        short = tf.minimum(h, w)
        scale = tf.cast(size, tf.float32) / tf.cast(short, tf.float32)
        nh = tf.cast(tf.math.ceil(tf.cast(h, tf.float32) * scale), tf.int32)
        nw = tf.cast(tf.math.ceil(tf.cast(w, tf.float32) * scale), tf.int32)
        img = tf.image.resize(img, (nh, nw), antialias=True)  # float32 0-255
        top = (nh - size) // 2
        left = (nw - size) // 2
        img = img[top : top + size, left : left + size, :]
        yield np.clip(np.asarray(img) / 255.0, 0.0, 1.0).astype(np.float32)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("raw_dir", help="root holding <split>/<class>/*.JPEG")
    ap.add_argument("out_dir")
    ap.add_argument("--split", default="train")
    ap.add_argument("--size", type=int, default=256,
                    help="stored side; must be >= data.image_size")
    ap.add_argument("--shard-items", type=int, default=1024)
    ap.add_argument("--dtype", default="uint8", choices=["uint8", "float32"])
    ap.add_argument("--seed", type=int, default=0,
                    help="class-mixing shuffle of the file order")
    ap.add_argument("--limit", type=int, default=0,
                    help="stop after N images (0 = all; for smoke runs)")
    args = ap.parse_args()

    split_dir = os.path.join(args.raw_dir, args.split)
    classes = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    if not classes:
        print(f"no class directories under {split_dir}", file=sys.stderr)
        return 2
    pairs = []  # (path, label)
    for label, cls in enumerate(classes):
        for p in sorted(
            glob.glob(os.path.join(split_dir, cls, "*"))
        ):
            if os.path.isfile(p):
                pairs.append((p, label))
    rng = np.random.default_rng(args.seed)
    rng.shuffle(pairs)
    if args.limit:
        pairs = pairs[: args.limit]

    os.makedirs(args.out_dir, exist_ok=True)
    buf_x, buf_y, shard_idx, written = [], [], 0, 0

    def flush():
        nonlocal buf_x, buf_y, shard_idx
        if not buf_x:
            return
        x = np.stack(buf_x)
        np.save(
            os.path.join(
                args.out_dir, f"{args.split}_images_{shard_idx:03d}.npy"
            ),
            x,
        )
        np.save(
            os.path.join(
                args.out_dir, f"{args.split}_labels_{shard_idx:03d}.npy"
            ),
            np.asarray(buf_y, np.int32),
        )
        shard_idx += 1
        buf_x, buf_y = [], []

    files = [p for p, _ in pairs]
    labels = [y for _, y in pairs]
    for img, y in zip(iter_decoded(files, args.size), labels):
        if args.dtype == "uint8":
            # Convert per image, not at flush: a float32 shard buffer
            # would hold 4x the bytes of the uint8 it becomes.
            img = np.round(img * 255.0).astype(np.uint8)
        buf_x.append(img)
        buf_y.append(y)
        written += 1
        if len(buf_x) >= args.shard_items:
            flush()
    flush()
    meta = {
        "split": args.split, "images": written, "classes": len(classes),
        "size": args.size, "dtype": args.dtype, "shards": shard_idx,
        "class_names": classes,
    }
    with open(
        os.path.join(args.out_dir, f"{args.split}_meta.json"), "w"
    ) as fh:
        json.dump(meta, fh, indent=1)
    print(json.dumps({k: v for k, v in meta.items() if k != "class_names"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
