#!/usr/bin/env python
"""Offline JPEG → ``.npy`` shard producer for the ImageNet loader (C16).

The training-path loaders read pre-decoded, fixed-shape shards
(``{split}_images_XXX.npy`` + ``{split}_labels_XXX.npy`` — data/shards.py)
because per-step JPEG decode on the host would starve the chip (SURVEY §7
hard part 5). This tool is the missing producer half for a real ImageNet
copy: it walks the standard per-class layout

    <raw_dir>/<split>/<wnid_or_class_name>/*.JPEG

decodes with TensorFlow's C++ JPEG decoder (tf is already in the image —
no new dependency; tf is used for IO only, nothing touches the training
path), resizes the short side to ``--size`` and center-crops to
``size x size``, and writes shards the loader memmaps directly:

    python tools/decode_imagenet.py <raw_dir> <out_dir> --split train \
        [--size 256] [--shard-items 1024] [--dtype uint8|float32] [--limit N]

Labels are the sorted class-directory order (the standard wnid->index
convention). ``--dtype uint8`` stores raw 0-255 pixels at 1/4 the disk of
float32; the loader rescales to [0,1] on gather before the augment kernel
normalizes, so stored dtype never changes training statistics.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # repo root: the sealed-save helper lives in the package

_IMAGE_EXTS = (".jpeg", ".jpg", ".png", ".bmp")


def iter_decoded(files, labels, size: int):
    """Yield ``(size x size x 3 float32 [0,1] image, label)`` pairs.

    A ``tf.data`` pipeline so the C++ JPEG decoder runs on all cores
    (``num_parallel_calls=AUTOTUNE``) — a sequential per-image loop would
    take hours over a real train split. Labels travel WITH their file
    through the pipeline, so ``ignore_errors`` (undecodable files are
    skipped with tf's warning, not a crash) can never misalign pairs.
    """
    import tensorflow as tf  # IO-only; never imported by the training path

    def decode(path, label):
        img = tf.io.decode_image(
            tf.io.read_file(path), channels=3, expand_animations=False
        )  # JPEG/PNG/BMP; uint8 HWC
        h = tf.shape(img)[0]
        w = tf.shape(img)[1]
        short = tf.minimum(h, w)
        scale = tf.cast(size, tf.float32) / tf.cast(short, tf.float32)
        nh = tf.cast(tf.math.ceil(tf.cast(h, tf.float32) * scale), tf.int32)
        nw = tf.cast(tf.math.ceil(tf.cast(w, tf.float32) * scale), tf.int32)
        img = tf.image.resize(img, (nh, nw), antialias=True)  # float32 0-255
        top = (nh - size) // 2
        left = (nw - size) // 2
        img = img[top : top + size, left : left + size, :]
        img = tf.clip_by_value(img / 255.0, 0.0, 1.0)
        img.set_shape((size, size, 3))
        return img, label

    ds = tf.data.Dataset.from_tensor_slices(
        (list(files), np.asarray(labels, np.int32))
    )
    ds = ds.map(decode, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.ignore_errors(log_warning=True)
    ds = ds.prefetch(tf.data.AUTOTUNE)
    for img, label in ds.as_numpy_iterator():
        yield img.astype(np.float32), int(label)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("raw_dir", help="root holding <split>/<class>/*.JPEG")
    ap.add_argument("out_dir")
    ap.add_argument("--split", default="train")
    ap.add_argument("--size", type=int, default=256,
                    help="stored side; must be >= data.image_size")
    ap.add_argument("--shard-items", type=int, default=1024)
    ap.add_argument("--dtype", default="uint8", choices=["uint8", "float32"])
    ap.add_argument("--seed", type=int, default=0,
                    help="class-mixing shuffle of the file order")
    ap.add_argument("--limit", type=int, default=0,
                    help="stop after N images (0 = all; for smoke runs)")
    ap.add_argument("--splits", default="",
                    help="comma-separated split dirs whose class lists are "
                         "unioned for label ids (default: conventional "
                         "split names under raw_dir); pin this when "
                         "raw_dir holds non-split directories")
    args = ap.parse_args()

    from frl_distributed_ml_scaffold_tpu.data.shards import (
        derive_label_classes,
    )

    split_dir = os.path.join(args.raw_dir, args.split)
    try:
        classes, _ = derive_label_classes(
            args.raw_dir, args.split, args.splits, args.out_dir
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    pairs = []  # (path, label)
    skipped = 0
    for label, cls in enumerate(classes):
        for p in sorted(
            glob.glob(os.path.join(split_dir, cls, "*"))
        ):
            # Extension filter: a real tree holds .DS_Store/checksums/
            # READMEs alongside images; they must be skipped here, not
            # crash the decoder hours in.
            if os.path.isfile(p) and p.lower().endswith(_IMAGE_EXTS):
                pairs.append((p, label))
            elif os.path.isfile(p):
                skipped += 1
    if skipped:
        print(f"skipping {skipped} non-image file(s)", file=sys.stderr)
    rng = np.random.default_rng(args.seed)
    rng.shuffle(pairs)
    if args.limit:
        pairs = pairs[: args.limit]

    os.makedirs(args.out_dir, exist_ok=True)
    buf_x, buf_y, shard_idx, written = [], [], 0, 0

    def flush():
        nonlocal buf_x, buf_y, shard_idx
        if not buf_x:
            return
        from frl_distributed_ml_scaffold_tpu.data.shards import sealed_save

        x = np.stack(buf_x)
        # Sealed (tmp+rename) writes, DATA before LABELS: the streaming
        # tier treats the labels shard as the pair's commit marker, so a
        # reader never samples a pair whose halves are mid-write.
        sealed_save(
            os.path.join(
                args.out_dir, f"{args.split}_images_{shard_idx:03d}.npy"
            ),
            x,
        )
        sealed_save(
            os.path.join(
                args.out_dir, f"{args.split}_labels_{shard_idx:03d}.npy"
            ),
            np.asarray(buf_y, np.int32),
        )
        shard_idx += 1
        buf_x, buf_y = [], []

    files = [p for p, _ in pairs]
    labels = [y for _, y in pairs]
    for img, y in iter_decoded(files, labels, args.size):
        if args.dtype == "uint8":
            # Convert per image, not at flush: a float32 shard buffer
            # would hold 4x the bytes of the uint8 it becomes.
            img = np.round(img * 255.0).astype(np.uint8)
        buf_x.append(img)
        buf_y.append(y)
        written += 1
        if len(buf_x) >= args.shard_items:
            flush()
    flush()
    meta = {
        "split": args.split, "images": written, "classes": len(classes),
        "size": args.size, "dtype": args.dtype, "shards": shard_idx,
        "class_names": classes,
    }
    with open(
        os.path.join(args.out_dir, f"{args.split}_meta.json"), "w"
    ) as fh:
        json.dump(meta, fh, indent=1)
    print(json.dumps({k: v for k, v in meta.items() if k != "class_names"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
