#!/usr/bin/env python
"""Optimizer convergence-sanity harness (VERDICT r4 next-round #1).

The round-4 on-chip sweep measured adafactor/lion ~+5% step throughput over
adamw on the GPT-2 flagship at the same operating point (mb4 remat=none:
31.7 / 31.6 vs 30.3 samples/sec/chip — evidence_r4/perf_sweep2.log), and
adafactor's factored second moment additionally frees ~2 bytes/param of
optimizer HBM. Throughput alone can't justify a recipe change: a faster
optimizer that converges worse is a regression. This harness runs the SAME
tiny GPT LM task under each optimizer for N steps on the CPU sim and
reports final smoothed losses, so the recipe decision is recorded with
loss data next to the throughput data (docs/perf_playbook.md "Optimizer
choice on the flagship").

    JAX_PLATFORMS=cpu python tools/opt_convergence.py [--steps 300]

Emits one JSONL row per optimizer plus a verdict row comparing each
candidate's final loss against adamw's with the tolerance used by the
regression pin in tests/test_optimizers.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _force_cpu() -> None:
    """Pin the CPU backend UNCONDITIONALLY before any backend initializes:
    the environment exports JAX_PLATFORMS=axon and the sitecustomize pins
    it again at the jax.config level, so both must be overwritten — a
    setdefault or env-var-only override would faithfully re-select the
    (possibly down) relay. This is a CPU-sim analysis tool; it must never
    touch the chip."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


#: Model-scale presets. "tiny" (~0.9M params) separates optimizers in
#: seconds; "10m" (~10.4M params: d=384, L=4, T=256, V=8192) is the
#: 10–30M-param proxy the adafactor recipe-LR decision is pinned at —
#: big enough that the RELATIVE update's RMS(param) scaling and the
#: factored second moment behave like the flagship's, small enough that
#: >=1k steps complete on the CPU sim (ISSUE r6 satellite; evidence in
#: evidence_r6/opt_convergence_10m.log).
SCALES = {
    "tiny": [
        "model.num_layers=2", "model.num_heads=4", "model.hidden_dim=128",
        "model.seq_len=128", "model.vocab_size=512",
        "data.seq_len=128", "data.vocab_size=512",
    ],
    "10m": [
        "model.num_layers=4", "model.num_heads=6", "model.hidden_dim=384",
        "model.seq_len=256", "model.vocab_size=8192",
        "data.seq_len=256", "data.vocab_size=8192",
    ],
}


def run_one(opt_name: str, steps: int, lr: float, scale: str = "tiny") -> dict:
    import gc

    import jax

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    # Release the previous combo's params/opt-state/executables BEFORE this
    # one allocates (same settle as tools/perf_sweep.py build()): at the
    # 10m scale, three accumulated live Trainers are what silently killed
    # the first 1k-step evidence run between configs 3 and 4.
    gc.collect()
    jax.clear_caches()
    gc.collect()

    # GPT on the synthetic-LM task: same model family and loss surface
    # as the flagship, at a SCALES preset. The
    # synthetic stream has learnable structure (repeating n-gram statistics),
    # so loss drops far below ln(vocab) and optimizers separate.
    cfg = apply_overrides(get_config("gpt2_medium_zero1"), SCALES[scale] + [
        "data.global_batch_size=8",
        "trainer.grad_accum=1", "trainer.remat=none",
        "trainer.log_every=1000000", "trainer.total_steps=%d" % steps,
        "optimizer.name=%s" % opt_name,
        "optimizer.learning_rate=%g" % lr,
        "optimizer.warmup_steps=20",
        "mesh.fsdp=1", "mesh.data=-1",
        "precision.policy=fp32",
        "checkpoint.enabled=false",
    ])
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    tail = losses[-max(1, steps // 10):]
    return {
        "optimizer": opt_name,
        "lr": lr,
        "steps": steps,
        "scale": scale,
        "loss_first": round(losses[0], 4),
        # Early-trajectory marker: what the regression pin in
        # tests/test_optimizers.py can afford to re-measure.
        "loss_step40": round(losses[min(39, steps - 1)], 4),
        "loss_final_mean": round(sum(tail) / len(tail), 4),
        "loss_min": round(min(losses), 4),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--scale", choices=sorted(SCALES), default="tiny")
    args = ap.parse_args()
    _force_cpu()

    # Per-optimizer LR grids at standard ratios: lion wants ~3-10x below
    # adamw (Chen et al. 2023); adafactor's update is RELATIVE (scaled by
    # RMS(param)), so its working LR sits ~30-100x above adamw's — the
    # first run of this tool proved the point the hard way (adafactor at
    # the adamw 3e-4: loss 6.26 -> 6.20 in 300 steps, i.e. barely moved,
    # vs 4.07 for adamw; see evidence_r5/opt_convergence.log).
    grid = {
        "adamw": [3e-4],
        "adafactor": [1e-2, 3e-2],
        "lion": [1e-4, 3e-4],
    }
    if args.scale == "10m":
        # The recipe-LR de-risk run: bracket the pinned 1e-2 from both
        # sides; lion is out of scope for this decision.
        grid = {"adamw": [3e-4], "adafactor": [3e-3, 1e-2, 3e-2]}
    rows = []
    for name, lrs in grid.items():
        for lr in lrs:
            r = run_one(name, args.steps, lr, scale=args.scale)
            rows.append(r)
            print(json.dumps(r), flush=True)
    best = {}
    for r in rows:
        cur = best.get(r["optimizer"])
        if cur is None or r["loss_final_mean"] < cur["loss_final_mean"]:
            best[r["optimizer"]] = r
    base = best["adamw"]
    verdict = {
        "mode": "verdict",
        "tolerance": 1.10,
        "best_lr_per_optimizer": {
            k: v["lr"] for k, v in sorted(best.items())
        },
        "candidates_within_tolerance": sorted(
            k for k, v in best.items()
            if v["loss_final_mean"] <= base["loss_final_mean"] * 1.10
        ),
    }
    print(json.dumps(verdict), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
