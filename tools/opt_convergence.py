#!/usr/bin/env python
"""Optimizer convergence-sanity harness (VERDICT r4 next-round #1).

The round-4 on-chip sweep measured adafactor/lion ~+5% step throughput over
adamw on the GPT-2 flagship at the same operating point (mb4 remat=none:
31.7 / 31.6 vs 30.3 samples/sec/chip — evidence_r4/perf_sweep2.log), and
adafactor's factored second moment additionally frees ~2 bytes/param of
optimizer HBM. Throughput alone can't justify a recipe change: a faster
optimizer that converges worse is a regression. This harness runs the SAME
tiny GPT LM task under each optimizer for N steps on the CPU sim and
reports final smoothed losses, so the recipe decision is recorded with
loss data next to the throughput data (docs/perf_playbook.md "Optimizer
choice on the flagship").

    JAX_PLATFORMS=cpu python tools/opt_convergence.py [--steps 300]

Emits one JSONL row per optimizer plus a verdict row comparing each
candidate's final loss against adamw's with the tolerance used by the
regression pin in tests/test_optimizers.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _force_cpu() -> None:
    """Pin the CPU backend UNCONDITIONALLY before any backend initializes:
    the environment exports JAX_PLATFORMS=axon and the sitecustomize pins
    it again at the jax.config level, so both must be overwritten — a
    setdefault or env-var-only override would faithfully re-select the
    (possibly down) relay. This is a CPU-sim analysis tool; it must never
    touch the chip."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_one(opt_name: str, steps: int, lr: float) -> dict:
    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    # Tiny GPT on the synthetic-LM task: same model family and loss surface
    # as the flagship, sized so 300 steps take seconds on the CPU sim. The
    # synthetic stream has learnable structure (repeating n-gram statistics),
    # so loss drops far below ln(vocab) and optimizers separate.
    cfg = apply_overrides(get_config("gpt2_medium_zero1"), [
        "model.num_layers=2", "model.num_heads=4", "model.hidden_dim=128",
        "model.seq_len=128", "model.vocab_size=512",
        "data.seq_len=128", "data.vocab_size=512",
        "data.global_batch_size=8",
        "trainer.grad_accum=1", "trainer.remat=none",
        "trainer.log_every=1000000", "trainer.total_steps=%d" % steps,
        "optimizer.name=%s" % opt_name,
        "optimizer.learning_rate=%g" % lr,
        "optimizer.warmup_steps=20",
        "mesh.fsdp=1", "mesh.data=-1",
        "precision.policy=fp32",
        "checkpoint.enabled=false",
    ])
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    for step in range(steps):
        batch = trainer.pipeline.global_batch(step)
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    tail = losses[-max(1, steps // 10):]
    return {
        "optimizer": opt_name,
        "lr": lr,
        "steps": steps,
        "loss_first": round(losses[0], 4),
        "loss_final_mean": round(sum(tail) / len(tail), 4),
        "loss_min": round(min(losses), 4),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    args = ap.parse_args()
    _force_cpu()

    # Per-optimizer LR grids at standard ratios: lion wants ~3-10x below
    # adamw (Chen et al. 2023); adafactor's update is RELATIVE (scaled by
    # RMS(param)), so its working LR sits ~30-100x above adamw's — the
    # first run of this tool proved the point the hard way (adafactor at
    # the adamw 3e-4: loss 6.26 -> 6.20 in 300 steps, i.e. barely moved,
    # vs 4.07 for adamw; see evidence_r5/opt_convergence.log).
    grid = {
        "adamw": [3e-4],
        "adafactor": [1e-2, 3e-2],
        "lion": [1e-4, 3e-4],
    }
    rows = []
    for name, lrs in grid.items():
        for lr in lrs:
            r = run_one(name, args.steps, lr)
            rows.append(r)
            print(json.dumps(r), flush=True)
    best = {}
    for r in rows:
        cur = best.get(r["optimizer"])
        if cur is None or r["loss_final_mean"] < cur["loss_final_mean"]:
            best[r["optimizer"]] = r
    base = best["adamw"]
    verdict = {
        "mode": "verdict",
        "tolerance": 1.10,
        "best_lr_per_optimizer": {
            k: v["lr"] for k, v in sorted(best.items())
        },
        "candidates_within_tolerance": sorted(
            k for k, v in best.items()
            if v["loss_final_mean"] <= base["loss_final_mean"] * 1.10
        ),
    }
    print(json.dumps(verdict), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
