#!/usr/bin/env python
"""Flash-kernel long-context block sweep (BASELINE.md long-context table).

Round-2 finding: the (512, 512) block optimum was tuned at T=1024, yet the
kernel's long-T efficiency was judged from that same tiling — 38 TFLOP/s
at T=32k vs 197 peak. This sweep separates "the grid is bound elsewhere"
from "the blocks are wrong at long T": block_q x block_k over T up to 64k,
fwd+bwd through the custom-VJP Pallas kernel, one JSONL row each.

    python tools/flash_sweep.py                 # full sweep (live TPU)
    python tools/flash_sweep.py --t 32768       # one sequence length
    python tools/flash_sweep.py --blocks 512    # one block candidate

Timing: device_get of a scalar (the relay's block_until_ready is a slow
stream-sync RPC and reports donated buffers ready — utils/timing.py).
FLOPs convention (matches BASELINE.md): causal fwd = 2·B·H·T²·D
(two matmuls over the lower triangle, MAC=2), bwd = 2.5x fwd (FA-2's five
backward matmuls), total 7·B·H·T²·D.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sweep(args) -> int:
    import jax

    # The axon sitecustomize pins jax_platforms at the config level, which
    # beats the env var — honor JAX_PLATFORMS=cpu for harness smoke runs.
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.ops.flash_attention import (
        flash_attention,
    )

    b, h, d = args.batch, args.heads, args.head_dim
    lengths = [args.t] if args.t else [8192, 16384, 32768, 65536]
    blocks = (
        [(args.blocks, args.blocks)]
        if args.blocks
        else [(256, 256), (512, 512), (1024, 512), (512, 1024), (1024, 1024),
              (2048, 512), (512, 2048)]
    )

    for t in lengths:
        key = jax.random.key(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, t, h, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, t, h, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, t, h, d), jnp.bfloat16)
        flops = 7.0 * b * h * float(t) * t * d  # fwd + 2.5x bwd, causal

        for bq, bk in blocks:
            if bq > t or bk > t:
                continue

            @jax.jit
            @functools.partial(jax.value_and_grad, argnums=(0, 1, 2))
            def fwd_bwd(q_, k_, v_, _bq=bq, _bk=bk):
                out = flash_attention(
                    q_, k_, v_, causal=True, block_q=_bq, block_k=_bk
                )
                return jnp.sum(out.astype(jnp.float32))

            rec = {"t": t, "block_q": bq, "block_k": bk}
            try:
                loss, grads = fwd_bwd(q, k, v)  # compile + settle
                # Settle on the grads too: device_get of the scalar loss
                # alone can return while the backward of the last iter is
                # still executing (collective_bench settle-ordering class).
                jax.device_get(jax.tree.map(lambda a: a.ravel()[0], grads))
                t0 = time.perf_counter()
                for _ in range(args.iters):
                    loss, grads = fwd_bwd(q, k, v)
                jax.device_get(jax.tree.map(lambda a: a.ravel()[0], grads))
                dt = (time.perf_counter() - t0) / args.iters
                rec.update(
                    fwd_bwd_ms=round(dt * 1e3, 2),
                    tflops=round(flops / dt / 1e12, 1),
                )
            except Exception as e:
                rec["error"] = str(e)[:200]
            print(json.dumps(rec), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--t", type=int, default=0, help="one T (default: ladder to 64k)")
    ap.add_argument("--blocks", type=int, default=0, help="one square block size")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()
    return sweep(args)


if __name__ == "__main__":
    raise SystemExit(main())
