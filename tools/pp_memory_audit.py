#!/usr/bin/env python
"""Activation-residency audit of the pipeline schedules (SURVEY C7).

Answers, with jaxpr-level residual accounting, the question behind 1F1B:
how much activation memory must the backward hold under each schedule?
The scan-autodiff GPipe/circular formulation saves every tick's stage
activations until the reverse timeline consumes them — O(v·M + S) tick
buffers — where a hand-scheduled 1F1B holds O(S) microbatches in flight
per stage. This tool measures the actual forward→backward residuals of
the REAL loss function (``jax._src.ad_checkpoint.saved_residuals`` — the
same accounting ``jax.ad_checkpoint.print_saved_residuals`` prints, and
immune to XLA:CPU's CSE which silently undoes recompute in
``memory_analysis``):

    python tools/pp_memory_audit.py [--layers 8] [--batch 16] [...]

Reported per schedule: total residual bytes, the per-tick-stacked subset
(leading dim = v·M+S-1 — the part 1F1B eliminates), everything else
(embeddings/head — schedule-independent), and the per-stage residency
after ``pipe`` sharding. ``--remat full`` shows jax.checkpoint collapsing
top-level residuals to the inputs (peak then moves inside the recompute).
Emits one JSON line per variant plus a table; docs/perf_playbook.md
records the conclusions.

``--flagship`` switches to the single-chip GPT-2-medium audit (VERDICT r3
next-round #3): sweep (trainer.remat | model.block_remat) x microbatch at
the REAL protocol shapes (L=24, D=1024, T=1024, flash attention, chunked
LM loss) and report, per variant, the forward->backward residual bytes
the backward must hold, next to the config's resident-state bytes (fp32
master params + AdamW mu/nu + fp32 grads + bf16 compute copy), so
"does microbatch 8 fit in 15.75G?" is answerable from residual
accounting BEFORE burning relay time:

    python tools/pp_memory_audit.py --flagship [--mb 4 8 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _residual_bytes(res) -> tuple[int, dict]:
    by_shape: dict = {}
    total = 0
    for aval, _src in res:
        if not hasattr(aval, "shape"):
            continue
        nbytes = int(aval.size) * aval.dtype.itemsize
        total += nbytes
        key = tuple(aval.shape)
        by_shape[key] = by_shape.get(key, 0) + nbytes
    return total, by_shape


def audit_one(args, sched: str, overrides: list[str], remat: str) -> dict:
    import jax
    import jax.numpy as jnp
    from jax._src.ad_checkpoint import saved_residuals

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.trainer.tasks import example_input
    from frl_distributed_ml_scaffold_tpu.trainer.train_step import _remat_wrap

    base = [
        f"model.num_layers={args.layers}",
        f"model.hidden_dim={args.hidden}",
        f"model.num_heads={args.heads}",
        f"model.seq_len={args.seq}",
        f"model.vocab_size={args.vocab}",
        f"data.seq_len={args.seq}",
        f"data.vocab_size={args.vocab}",
        f"data.global_batch_size={args.batch}",
        "model.lm_loss_chunk=0",
        "trainer.grad_accum=1",
        "checkpoint.enabled=false",
        "data.prefetch=0",
        "precision.policy=bf16_mixed",
        f"trainer.remat={remat}",
    ]
    cfg = apply_overrides(get_config("gpt2_medium_zero1"), base + overrides)
    trainer = Trainer(cfg)
    example = {
        k: jnp.asarray(v)
        for k, v in example_input(
            cfg.data, cfg.model, batch_size=cfg.data.global_batch_size
        ).items()
    }
    wrapped = _remat_wrap(trainer.loss_fn, remat)

    def scalar_loss(params):
        loss, _ = wrapped(
            params, trainer.state_shapes.extras, example,
            jax.random.key(0), True,
        )
        return loss

    res = trainer._mesh_scoped(saved_residuals)(
        scalar_loss, trainer.state_shapes.params
    )
    total, by_shape = _residual_bytes(res)

    s = cfg.model.pipeline_stages
    v = max(1, cfg.model.pipeline_circular_repeat) if s > 1 else 1
    m = cfg.model.pipeline_microbatches or s
    ticks = v * m + s - 1 if s > 1 else 0
    # Param-shaped residuals (the weights the backward re-reads) are
    # resident state, not schedule cost — exclude them from the
    # activation figure by subtracting exact param-leaf sizes.
    param_bytes = sum(
        int(l.size) * l.dtype.itemsize
        for l in jax.tree.leaves(trainer.state_shapes.params)
    )
    ticked = sum(
        b for shape, b in by_shape.items() if ticks and shape[:1] == (ticks,)
    )
    rec = {
        "schedule": sched,
        "remat": remat,
        "ticks": ticks,
        "residual_mb": round(total / 1e6, 1),
        "residual_minus_params_mb": round((total - param_bytes) / 1e6, 1),
        "tick_stacked_mb": round(ticked / 1e6, 1),
        "other_mb": round((total - param_bytes - ticked) / 1e6, 1),
        # Tick-stacked residuals carry [ticks, S, mb, ...] with the S dim
        # pipe-sharded: per-stage residency is the 1/S slice.
        "tick_stacked_per_stage_mb": round(
            ticked / max(1, s) / 1e6, 1
        ),
    }
    print(json.dumps(rec), flush=True)
    return rec


def flagship_one(mb: int, remat: str, block_remat: str) -> dict:
    import jax
    import jax.numpy as jnp
    from jax._src.ad_checkpoint import saved_residuals

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.trainer.tasks import example_input
    from frl_distributed_ml_scaffold_tpu.trainer.train_step import _remat_wrap

    cfg = apply_overrides(
        get_config("gpt2_medium_zero1"),
        [
            # The BENCH_TABLE protocol operating point (bench.py
            # ALL_CONFIGS), batch swept by the caller.
            f"data.global_batch_size={mb}",
            "trainer.grad_accum=1",
            "model.attention=flash",
            "model.lm_loss_chunk=128",
            # Single-chip semantics on the 8-device CPU sim: every mesh
            # axis 1, as on the real v5e chip the numbers are for.
            "mesh.data=1", "mesh.fsdp=1", "mesh.model=1",
            "mesh.pipe=1", "mesh.seq=1", "mesh.expert=1",
            f"trainer.remat={remat}",
            f"model.block_remat={block_remat}",
            "checkpoint.enabled=false",
            "data.prefetch=0",
        ],
    )
    trainer = Trainer(cfg)
    example = {
        k: jnp.asarray(v)
        for k, v in example_input(
            cfg.data, cfg.model, batch_size=mb
        ).items()
    }
    wrapped = _remat_wrap(trainer.loss_fn, remat)

    def scalar_loss(params):
        loss, _ = wrapped(
            params, trainer.state_shapes.extras, example,
            jax.random.key(0), True,
        )
        return loss

    res = trainer._mesh_scoped(saved_residuals)(
        scalar_loss, trainer.state_shapes.params
    )
    total, by_shape = _residual_bytes(res)
    param_bytes = sum(
        int(l.size) * l.dtype.itemsize
        for l in jax.tree.leaves(trainer.state_shapes.params)
    )
    # Resident state for this config (ZeRO-1 on one chip = unsharded):
    # fp32 master params, AdamW mu+nu (fp32, like_params), fp32 grads
    # held across the update, plus the bf16 compute-cast copy alive
    # through the backward.
    resident = param_bytes * (1 + 2 + 1) + param_bytes // 2
    act = total - param_bytes
    rec = {
        "mb": mb,
        "remat": remat,
        "block_remat": block_remat,
        "residual_minus_params_mb": round(act / 1e6, 1),
        "resident_state_mb": round(resident / 1e6, 1),
        "total_mb": round((act + resident) / 1e6, 1),
        "fits_15_75g": bool(act + resident < 15.75e9),
    }
    print(json.dumps(rec), flush=True)
    return rec


def moe_one(g: int, batch: int, experts: int, block_remat: str) -> dict:
    """Residual audit of the FULL MoE train step at real routed shapes
    (VERDICT r3 next-round #5): N = batch*1024 tokens, E experts, k=2,
    G routing groups. Separates the dispatch/combine one-hot tensors
    ([G, S, E, C] — the GSEC memory story) from everything else."""
    import jax
    import jax.numpy as jnp
    from jax._src.ad_checkpoint import saved_residuals

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.trainer.tasks import example_input

    cfg = apply_overrides(
        get_config("gpt2_moe"),
        [
            f"data.global_batch_size={batch}",
            f"model.moe.num_experts={experts}",
            f"model.moe.num_groups={g}",
            "model.attention=flash",
            "model.lm_loss_chunk=128",
            f"model.block_remat={block_remat}",
            "trainer.grad_accum=1",
            "checkpoint.enabled=false",
            "data.prefetch=0",
            "mesh.data=1", "mesh.fsdp=1", "mesh.model=1",
            "mesh.pipe=1", "mesh.seq=1", "mesh.expert=1",
        ],
    )
    trainer = Trainer(cfg)
    example = {
        k: jnp.asarray(v)
        for k, v in example_input(
            cfg.data, cfg.model, batch_size=batch
        ).items()
    }

    def scalar_loss(params):
        loss, _ = trainer.loss_fn(
            params, trainer.state_shapes.extras, example,
            jax.random.key(0), True,
        )
        return loss

    res = trainer._mesh_scoped(saved_residuals)(
        scalar_loss, trainer.state_shapes.params
    )
    total, by_shape = _residual_bytes(res)
    param_bytes = sum(
        int(l.size) * l.dtype.itemsize
        for l in jax.tree.leaves(trainer.state_shapes.params)
    )
    n = batch * cfg.model.seq_len
    s = n // g
    e = experts
    cap = max(1, int(cfg.model.moe.capacity_factor * s * 2 / e))
    # Dispatch/combine and their einsum partners carry the capacity dim —
    # count every residual whose trailing dims look like [.., E, C] or
    # [E, .., C, ..] (expert_in/out are [E, G, C, D]).
    gsec = sum(
        b for shape, b in by_shape.items()
        if (len(shape) >= 3 and shape[-2:] == (e, cap))
        or (len(shape) == 4 and shape[0] == e and shape[2] == cap)
    )
    rec = {
        "groups": g,
        "batch": batch,
        "experts": e,
        "capacity": cap,
        "block_remat": block_remat,
        "residual_minus_params_mb": round((total - param_bytes) / 1e6, 1),
        "gsec_tensors_mb": round(gsec / 1e6, 1),
        "other_mb": round((total - param_bytes - gsec) / 1e6, 1),
    }
    print(json.dumps(rec), flush=True)
    return rec


def moe_main(args) -> int:
    rows = []
    for br in ("none", "full"):
        for g in args.groups:
            rows.append(moe_one(g, args.batch, args.experts, br))
    print(
        f"\n{'G':>3s} {'block_remat':>11s} {'cap':>5s} "
        f"{'activations MB':>15s} {'GSEC MB':>9s} {'other MB':>9s}"
    )
    for r in rows:
        print(
            f"{r['groups']:3d} {r['block_remat']:>11s} {r['capacity']:5d} "
            f"{r['residual_minus_params_mb']:15.1f} "
            f"{r['gsec_tensors_mb']:9.1f} {r['other_mb']:9.1f}"
        )
    return 0


def flagship_main(args) -> int:
    variants = [
        ("dots", "none"),   # the round-3 protocol line (mb4 knee)
        ("none", "none"),
        ("full", "none"),
        ("none", "full"),
        ("none", "save_attn"),
    ]
    rows = []
    for mb in args.mb:
        for remat, br in variants:
            rows.append(flagship_one(mb, remat, br))
    print(
        f"\n{'mb':>3s} {'remat':>6s} {'block_remat':>11s} "
        f"{'activations MB':>15s} {'resident MB':>12s} {'total MB':>9s}  fits15.75G"
    )
    for r in rows:
        print(
            f"{r['mb']:3d} {r['remat']:>6s} {r['block_remat']:>11s} "
            f"{r['residual_minus_params_mb']:15.1f} "
            f"{r['resident_state_mb']:12.1f} {r['total_mb']:9.1f}  "
            f"{'yes' if r['fits_15_75g'] else 'NO'}"
        )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--repeat", type=int, default=2)
    ap.add_argument("--remat", default="none", choices=["none", "dots", "full"])
    ap.add_argument("--flagship", action="store_true",
                    help="single-chip GPT-2-medium remat-mode sweep")
    ap.add_argument("--mb", type=int, nargs="+", default=[4, 8, 16],
                    help="--flagship microbatch sizes")
    ap.add_argument("--moe", action="store_true",
                    help="MoE dispatch-memory audit at real routed shapes")
    ap.add_argument("--groups", type=int, nargs="+", default=[1, 8, 32],
                    help="--moe routing-group counts")
    ap.add_argument("--experts", type=int, default=64)
    args = ap.parse_args()
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Flagship/MoE modes audit the one-chip config — a single CPU
        # device keeps the mesh honest; the PP audit needs the 8-device sim.
        n = 1 if (args.flagship or args.moe) else 8
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.flagship:
        return flagship_main(args)
    if args.moe:
        return moe_main(args)

    gpipe_ov = [
        f"model.pipeline_stages={args.stages}",
        f"model.pipeline_microbatches={args.microbatches}",
        f"mesh.pipe={args.stages}", "mesh.data=2",
    ]
    circ_ov = gpipe_ov + [
        f"model.pipeline_circular_repeat={args.repeat}",
    ]
    sr = ["model.pipeline_stage_remat=true"]
    variants = [
        ("plain", ["model.pipeline_stages=1", "mesh.pipe=1", "mesh.data=8"]),
        ("gpipe", gpipe_ov),
        ("gpipe+sr", gpipe_ov + sr),
        ("circular", circ_ov),
        ("circular+sr", circ_ov + sr),
    ]
    rows = [audit_one(args, s, o, args.remat) for s, o in variants]
    print(
        f"\n{'schedule':10s} {'ticks':>5s} {'resid-params MB':>16s} "
        f"{'tick-stacked MB':>16s} {'per-stage MB':>13s}"
    )
    for r in rows:
        print(
            f"{r['schedule']:10s} {r['ticks']:5d} "
            f"{r['residual_minus_params_mb']:16.1f} "
            f"{r['tick_stacked_mb']:16.1f} "
            f"{r['tick_stacked_per_stage_mb']:13.1f}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
