#!/usr/bin/env python
"""Average the params of the last K checkpoints into one params file.

Checkpoint averaging (Polyak-style, over saved snapshots rather than every
step like ``trainer.ema_decay``) is the classic cheap eval boost for
translation/LM recipes. Output is the same flax-msgpack format as
``tools/import_hf_gpt2.py``, so the result plugs into
``trainer.init_params_path`` or an eval-only run.

    python tools/avg_checkpoints.py --workdir /runs/gpt2_medium_zero1 \
        --last 3 --out avg.msgpack

The averaging runs on CPU over host arrays — no TPU needed, safe on a
machine without the training topology.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", required=True,
                    help="the run's <workdir>/<config-name> directory "
                         "(contains ckpt/)")
    ap.add_argument("--last", type=int, default=3,
                    help="how many most-recent checkpoints to average")
    ap.add_argument("--out", required=True, help="output .msgpack path")
    args = ap.parse_args()
    if args.last <= 0:
        ap.error(f"--last must be >= 1, got {args.last}")

    import json

    import jax

    jax.config.update("jax_platforms", "cpu")

    from frl_distributed_ml_scaffold_tpu.config import (
        ExperimentConfig,
        apply_overrides,
        config_from_dict,
    )
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.utils.trees import tree_param_count
    from import_hf_gpt2 import save_params  # same serialization surface

    run_dir = os.path.abspath(args.workdir)
    cfg_path = os.path.join(run_dir, "config.json")
    if not os.path.isfile(cfg_path):
        print(f"no config.json in {run_dir} (written by fit() since r2); "
              "pass the run directory, not the workdir root", file=sys.stderr)
        return 1
    with open(cfg_path) as fh:
        cfg = config_from_dict(ExperimentConfig, json.load(fh))
    # Rebuild on THIS host's topology (1 CPU device): the checkpoint
    # restore reshards from the writer's topology — the same mechanism
    # the elastic path uses, so the tool works on any machine.
    cfg = apply_overrides(cfg, [
        "mesh.pipe=1", "mesh.data=-1", "mesh.fsdp=1", "mesh.seq=1",
        "mesh.expert=1", "mesh.model=1", "mesh.dcn_data=1",
        "checkpoint.enabled=true", "data.prefetch=0",
        # This host need not satisfy TPU-only knobs or find aux files:
        # the tool only rebuilds shapes/shardings and reads params.
        "trainer.offload_opt_state=false", "trainer.init_params_path=",
        # Locate the ckpt/ by the DIRECTORY the user named, not the name
        # recorded in config.json — archived/renamed runs must work.
        f"name={os.path.basename(run_dir)}",
        f"workdir={os.path.dirname(run_dir)}",
    ])
    trainer = Trainer(cfg)
    ck = trainer.checkpointer
    steps = ck.all_steps()[-args.last:]
    if not steps:
        print(f"no checkpoints under {run_dir}/ckpt", file=sys.stderr)
        return 1

    acc = None
    for step in steps:
        # Params-only partial restore (ocp.PLACEHOLDER skips the optimizer
        # moments/extras): ~3x less I/O and host RAM than the full state.
        state = ck.restore_params_only(
            trainer.state_shapes, trainer.state_shardings, step
        )
        params = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x), np.float64), state
        )
        acc = params if acc is None else jax.tree.map(np.add, acc, params)
        print(f"  + step {step}", file=sys.stderr)
    avg = jax.tree.map(
        lambda x: (x / len(steps)).astype(np.float32), acc
    )
    save_params(avg, args.out)
    print(
        f"wrote {args.out}: mean of steps {steps} "
        f"({tree_param_count(avg)/1e6:.2f}M params)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
