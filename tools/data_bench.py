#!/usr/bin/env python
"""Input-pipeline throughput microbench (SURVEY §7 hard part 5).

The chip-side benchmark (bench.py) deliberately excludes the loader; this
tool answers the complementary question — can the host pipeline outrun the
chip? — by timing each real-data loader's ``batch()`` on generated corpora,
native C++ core vs numpy fallback. One JSONL line per measurement.

    python tools/data_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

# Host-only tool: never bring up an accelerator backend (the axon relay can
# hang indefinitely when unreachable, and nothing here needs a device).
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig  # noqa: E402
from frl_distributed_ml_scaffold_tpu.data import native  # noqa: E402


def timed(fn, *, n=20, warm=3) -> float:
    for _ in range(warm):
        fn()
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def emit(loader, impl, batch, dt, samples):
    print(
        json.dumps(
            {
                "loader": loader,
                "impl": impl,
                "batch_size": batch,
                "batch_ms": round(dt * 1e3, 2),
                "samples_per_sec": round(samples / dt, 1),
            }
        ),
        flush=True,
    )


def with_fallback(fn):
    """Run fn with the native core masked off (numpy paths)."""
    real = native._load
    native._load = lambda: None
    try:
        return fn()
    finally:
        native._load = real


# Label honestly: without g++ the "native" measurement IS the numpy path.
NATIVE_IMPL = "native" if native.native_available() else "numpy (no native core)"


def bench_imagenet(root):
    from frl_distributed_ml_scaffold_tpu.data.imagenet import ImageNet

    rng = np.random.default_rng(0)
    d = root / "imagenet"
    d.mkdir()
    for shard in range(2):
        np.save(d / f"train_images_{shard:03d}.npy",
                rng.random((256, 64, 64, 3), np.float32))
        np.save(d / f"train_labels_{shard:03d}.npy",
                rng.integers(0, 100, 256))
    cfg = DataConfig(name="imagenet", data_dir=str(d), image_size=56,
                     num_classes=100, channels=3)
    src = ImageNet(cfg, split="train")
    assert not src.is_synthetic
    bs = 256
    step = iter(range(10**9))
    emit("imagenet_shards", NATIVE_IMPL, bs,
         timed(lambda: src.batch(next(step), bs)), bs)
    emit("imagenet_shards", "numpy", bs,
         with_fallback(lambda: timed(lambda: src.batch(next(step), bs))), bs)


def bench_lm(root):
    from frl_distributed_ml_scaffold_tpu.data.lm import TokenBinLM, write_token_bin

    d = root / "lm"
    d.mkdir()
    rng = np.random.default_rng(1)
    write_token_bin(str(d / "train.bin"),
                    rng.integers(0, 50000, size=4_000_000), vocab_size=50257)
    cfg = DataConfig(name="lm", data_dir=str(d), seq_len=1024, vocab_size=50257)
    src = TokenBinLM(cfg, split="train")
    assert not src.is_synthetic
    bs = 64
    step = iter(range(10**9))
    emit("lm_token_bin", NATIVE_IMPL, bs,
         timed(lambda: src.batch(next(step), bs)), bs)
    emit("lm_token_bin", "numpy", bs,
         with_fallback(lambda: timed(lambda: src.batch(next(step), bs))), bs)


def bench_video(root):
    from frl_distributed_ml_scaffold_tpu.data.video import (
        VideoClips,
        write_clip_shards,
    )

    d = root / "video"
    d.mkdir()
    rng = np.random.default_rng(2)
    write_clip_shards(
        str(d),
        rng.random((128, 8, 64, 64, 3)).astype(np.float32),
        rng.integers(0, 50, 128),
        shard_size=64,
    )
    cfg = DataConfig(name="video", data_dir=str(d), num_frames=8,
                     image_size=64, channels=3, num_classes=50)
    src = VideoClips(cfg, split="train")
    assert not src.is_synthetic
    bs = 32
    step = iter(range(10**9))
    emit("video_clips", NATIVE_IMPL, bs,
         timed(lambda: src.batch(next(step), bs), n=10), bs)
    emit("video_clips", "numpy", bs,
         with_fallback(lambda: timed(lambda: src.batch(next(step), bs), n=10)),
         bs)


if __name__ == "__main__":
    import pathlib

    with tempfile.TemporaryDirectory() as tmp:
        root = pathlib.Path(tmp)
        bench_imagenet(root)
        bench_lm(root)
        bench_video(root)
