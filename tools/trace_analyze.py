#!/usr/bin/env python
"""Summarize a jax.profiler TPU trace: top XLA ops by device time.

Pairs with the capture flow (BASELINE.md backlog, VERDICT r1 item 2):

    python - <<'PY'
    import jax
    ... warm up trainer ...
    jax.profiler.start_trace("/tmp/rn50_trace")
    ... N steps + device_get ...
    jax.profiler.stop_trace()
    PY
    python tools/trace_analyze.py /tmp/rn50_trace [top_n]

No tensorboard needed: the .xplane.pb is parsed with the protobuf module
that ships inside tensorflow (tensorflow.tsl.profiler.protobuf).
"""

from __future__ import annotations

import collections
import glob
import os
import sys


def find_xplane(root: str) -> str:
    hits = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no .xplane.pb under {root}")
    return hits[-1]  # latest capture


#: Op-name substrings that classify an XLA op as communication. The
#: overlap summary keys on these (fusion names embed the collective name).
COMM_OPS = ("all-gather", "all-reduce", "reduce-scatter",
            "collective-permute", "all-to-all")


def _merge(intervals):
    """Sorted union of (start, end) intervals."""
    out = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _intersection_len(xs, ys):
    """Total overlap length between two MERGED interval lists."""
    total, i, j = 0, 0, 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if a < b:
            total += b - a
        if xs[i][1] < ys[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_summary(line, emeta) -> None:
    """Comm-vs-compute overlap evidence for one device timeline.

    The number the overlap-scheduled FSDP A/B is after (perf_sweep
    gpt2_fsdp_overlap / docs/perf_playbook.md): how much collective time
    runs CONCURRENTLY with compute vs exposed on the critical path.
    Computed as an interval sweep over the XLA Ops lane: union the comm
    events' wall intervals, union the compute events', intersect.
    """
    comm, comp = [], []
    for e in line.events:
        name = emeta[e.metadata_id]
        iv = (e.offset_ps, e.offset_ps + e.duration_ps)
        if any(k in name for k in COMM_OPS):
            comm.append(iv)
        else:
            comp.append(iv)
    if not comm:
        print("  overlap: no collective ops in this lane")
        return
    comm_m, comp_m = _merge(comm), _merge(comp)
    comm_ms = sum(b - a for a, b in comm_m) / 1e9
    if comm_ms <= 0.0:
        # Async collective pairs can log zero-duration start/done marker
        # events; a lane with only those has no measurable comm window.
        print("  overlap: collective events carry no duration in this lane")
        return
    hidden_ms = _intersection_len(comm_m, comp_m) / 1e9
    exposed_ms = comm_ms - hidden_ms
    print(
        f"  overlap: comm {comm_ms:.2f} ms total, "
        f"{hidden_ms:.2f} ms hidden under compute "
        f"({100.0 * hidden_ms / comm_ms:.1f}%), "
        f"{exposed_ms:.2f} ms exposed"
    )


def main() -> int:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/jax_trace"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    path = find_xplane(root)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())

    tpu_planes = [
        p for p in xs.planes if p.name.startswith("/device:TPU")
    ]
    if not tpu_planes:
        # CPU-sim traces carry host thread lines, not per-op device
        # lanes — say so instead of printing nothing.
        print(
            f"no /device:TPU plane in {path} (planes: "
            f"{[p.name for p in xs.planes]}); capture on real TPU for "
            "the per-op table"
        )
        return 0
    for plane in tpu_planes:
        emeta = {m.id: m.name for m in plane.event_metadata.values()}
        for line in plane.lines:
            if line.name not in ("XLA Ops", "Steps"):
                continue
            agg: collections.Counter = collections.Counter()
            n_events: collections.Counter = collections.Counter()
            for e in line.events:
                agg[emeta[e.metadata_id]] += e.duration_ps
                n_events[emeta[e.metadata_id]] += 1
            total_ms = sum(agg.values()) / 1e9
            n_steps = len(line.events) if line.name == "Steps" else max(
                n_events.values(), default=1
            )
            print(f"\n== {plane.name} / {line.name}: {total_ms:.1f} ms total "
                  f"({len(line.events)} events)")
            if line.name == "Steps":
                for name, ps in sorted(agg.items()):
                    print(f"  step {name}: {ps / 1e9:.2f} ms")
                continue
            print(f"  {'ms/step':>8s} {'count':>6s}  op")
            for name, ps in agg.most_common(top_n):
                print(
                    f"  {ps / 1e9 / n_steps:8.2f} {n_events[name]:6d}  {name[:120]}"
                )
            overlap_summary(line, emeta)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
