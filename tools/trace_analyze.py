#!/usr/bin/env python
"""Summarize a jax.profiler TPU trace: top XLA ops by device time.

Pairs with the capture flow (BASELINE.md backlog, VERDICT r1 item 2):

    python - <<'PY'
    import jax
    ... warm up trainer ...
    jax.profiler.start_trace("/tmp/rn50_trace")
    ... N steps + device_get ...
    jax.profiler.stop_trace()
    PY
    python tools/trace_analyze.py /tmp/rn50_trace [top_n]

No tensorboard needed: the .xplane.pb is parsed with the protobuf module
that ships inside tensorflow (tensorflow.tsl.profiler.protobuf).
"""

from __future__ import annotations

import collections
import glob
import os
import sys


def find_xplane(root: str) -> str:
    hits = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no .xplane.pb under {root}")
    return hits[-1]  # latest capture


#: Op-name substrings that classify an XLA op as communication. The
#: overlap summary keys on these (fusion names embed the collective name).
#: Order matters: an op is bucketed under its FIRST match, so the
#: per-class breakdown stays deterministic for fusion names embedding
#: several (e.g. a fused reduce-scatter feeding a collective-permute).
COMM_OPS = ("all-gather", "all-reduce", "reduce-scatter",
            "collective-permute", "all-to-all")


def comm_class(name: str) -> str | None:
    """First COMM_OPS substring in ``name``, or None for compute ops.

    ``collective-permute`` is what the tp_overlap / collective-matmul
    ppermute rings lower to — the class the gpt2_tp_overlap A/B reads."""
    for k in COMM_OPS:
        if k in name:
            return k
    return None


def _merge(intervals):
    """Sorted union of (start, end) intervals."""
    out = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _intersection_len(xs, ys):
    """Total overlap length between two MERGED interval lists."""
    total, i, j = 0, 0, 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if a < b:
            total += b - a
        if xs[i][1] < ys[j][1]:
            i += 1
        else:
            j += 1
    return total


#: Decode-serving op classes (tools/serve_bench.py captures): the fused
#: decode kernel lowers to a Mosaic custom-call whose fusion/op names embed
#: the pallas kernel symbol; KV-cache writes are the per-row scatter /
#: dynamic-update-slice the gpt.py decode path emits. First match wins,
#: like COMM_OPS.
DECODE_KERNEL_OPS = ("decode_kernel", "flash_decode", "decode_attention")
CACHE_UPDATE_OPS = ("dynamic-update-slice", "dynamic_update_slice", "scatter")


def classify_decode(events) -> dict:
    """Decode-serving time split for one device timeline: fused
    decode-attention kernel time vs KV-cache update time vs everything
    else (projections, embedding, sampling). ``events`` is the same
    ``(name, start_ps, end_ps)`` span form ``classify_overlap`` takes —
    tests feed synthetic spans, ``main`` feeds the XLA Ops lane. Durations
    are summed per class (not interval-unioned: the question here is
    where the step's device time GOES, not what overlaps what)."""
    out = {"decode_kernel_ms": 0.0, "cache_update_ms": 0.0, "other_ms": 0.0}
    for name, a, b in events:
        dur = (b - a) / 1e9
        if any(k in name for k in DECODE_KERNEL_OPS):
            out["decode_kernel_ms"] += dur
        elif comm_class(name) is not None:
            # Collectives before the cache check: a sharded decode lane
            # carries e.g. "reduce-scatter" fusions whose name would
            # otherwise substring-match the bare "scatter" cache class —
            # comm time belongs to classify_overlap, not the cache split.
            out["other_ms"] += dur
        elif any(k in name for k in CACHE_UPDATE_OPS):
            out["cache_update_ms"] += dur
        else:
            out["other_ms"] += dur
    return out


def decode_summary(line, emeta) -> None:
    """Print the decode-serving split for one XLA Ops lane when the lane
    actually contains decode-attention kernel work (the serve_bench
    on-chip capture, BACKLOG R8-1)."""
    events = [
        (emeta[e.metadata_id], e.offset_ps, e.offset_ps + e.duration_ps)
        for e in line.events
    ]
    if not any(
        any(k in name for k in DECODE_KERNEL_OPS) for name, _, _ in events
    ):
        return
    stats = classify_decode(events)
    total = sum(stats.values())
    if total <= 0.0:
        return
    print(
        f"  decode: kernel {stats['decode_kernel_ms']:.2f} ms "
        f"({100.0 * stats['decode_kernel_ms'] / total:.1f}%), "
        f"cache update {stats['cache_update_ms']:.2f} ms "
        f"({100.0 * stats['cache_update_ms'] / total:.1f}%), "
        f"other {stats['other_ms']:.2f} ms"
    )


def classify_overlap(events) -> dict:
    """Comm-vs-compute overlap stats for one device timeline.

    ``events``: iterable of ``(name, start_ps, end_ps)`` spans (pure data —
    tests feed synthetic spans, ``main`` feeds the XLA Ops lane). Returns
    ``{"all": {...}, "<comm class>": {...}}`` where each value carries
    ``total_ms`` / ``hidden_ms`` / ``exposed_ms``: comm intervals are
    unioned (per class and overall), compute intervals unioned, and hidden
    time is their intersection — collective time running CONCURRENTLY with
    compute vs exposed on the critical path. The per-class split is what
    separates the FSDP schedule's all-gather/reduce-scatter from the
    tp_overlap rings' collective-permute in one capture.
    """
    comp = []
    by_class: dict[str, list] = {}
    for name, a, b in events:
        cls = comm_class(name)
        if cls is None:
            comp.append((a, b))
        else:
            by_class.setdefault(cls, []).append((a, b))
    comp_m = _merge(comp)
    out = {}
    all_comm = []
    for cls, ivs in by_class.items():
        merged = _merge(ivs)
        all_comm.extend(ivs)
        total = sum(b - a for a, b in merged)
        hidden = _intersection_len(merged, comp_m)
        out[cls] = {
            "total_ms": total / 1e9,
            "hidden_ms": hidden / 1e9,
            "exposed_ms": (total - hidden) / 1e9,
        }
    if all_comm:
        merged = _merge(all_comm)
        total = sum(b - a for a, b in merged)
        hidden = _intersection_len(merged, comp_m)
        out["all"] = {
            "total_ms": total / 1e9,
            "hidden_ms": hidden / 1e9,
            "exposed_ms": (total - hidden) / 1e9,
        }
    return out


def overlap_summary(line, emeta) -> None:
    """Print the overlap evidence for one XLA Ops lane (the number the
    overlap-schedule A/Bs are after — perf_sweep gpt2_fsdp_overlap /
    gpt2_tp_overlap, docs/perf_playbook.md)."""
    events = [
        (
            emeta[e.metadata_id],
            e.offset_ps,
            e.offset_ps + e.duration_ps,
        )
        for e in line.events
    ]
    stats = classify_overlap(events)
    if not stats:
        print("  overlap: no collective ops in this lane")
        return
    if stats["all"]["total_ms"] <= 0.0:
        # Async collective pairs can log zero-duration start/done marker
        # events; a lane with only those has no measurable comm window.
        print("  overlap: collective events carry no duration in this lane")
        return
    agg = stats["all"]
    print(
        f"  overlap: comm {agg['total_ms']:.2f} ms total, "
        f"{agg['hidden_ms']:.2f} ms hidden under compute "
        f"({100.0 * agg['hidden_ms'] / agg['total_ms']:.1f}%), "
        f"{agg['exposed_ms']:.2f} ms exposed"
    )
    for cls in COMM_OPS:
        s = stats.get(cls)
        if s is None or s["total_ms"] <= 0.0:
            continue
        print(
            f"    {cls:>18s}: {s['total_ms']:.2f} ms, "
            f"{s['hidden_ms']:.2f} hidden / {s['exposed_ms']:.2f} exposed"
        )


def lane_report(events, top_n: int = 20) -> dict:
    """Machine-readable summary of one XLA Ops lane (the ``--json`` unit).

    ``events``: ``(name, start_ps, end_ps)`` spans — the same pure-data
    form ``classify_overlap``/``classify_decode`` take, so synthetic
    spans golden-test the whole structure without an xplane file. The
    overlap classification here is the artifact PRs diff against each
    other (tests/golden/trace_analyze_lane.json).
    """
    agg: collections.Counter = collections.Counter()
    n_events: collections.Counter = collections.Counter()
    for name, a, b in events:
        agg[name] += b - a
        n_events[name] += 1
    n_steps = max(n_events.values(), default=1)
    overlap = {
        cls: {k: round(v, 6) for k, v in stats.items()}
        for cls, stats in classify_overlap(events).items()
    }
    has_decode = any(
        any(k in name for k in DECODE_KERNEL_OPS) for name, _, _ in events
    )
    return {
        "total_ms": round(sum(agg.values()) / 1e9, 6),
        "n_events": sum(n_events.values()),
        "top_ops": [
            {
                "op": name,
                "ms_per_step": round(ps / 1e9 / n_steps, 6),
                "total_ms": round(ps / 1e9, 6),
                "count": n_events[name],
            }
            for name, ps in agg.most_common(top_n)
        ],
        "overlap": overlap,
        "decode": (
            {k: round(v, 6) for k, v in classify_decode(events).items()}
            if has_decode
            else None
        ),
    }


def analyze(root: str, top_n: int = 20, *, quiet: bool = False) -> dict:
    """Parse the latest xplane capture under ``root``; print the human
    tables (unless ``quiet``) and return the ``--json`` report."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    path = find_xplane(root)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())

    report: dict = {"trace": path, "planes": []}
    tpu_planes = [
        p for p in xs.planes if p.name.startswith("/device:TPU")
    ]
    if not tpu_planes:
        # CPU-sim traces carry host thread lines, not per-op device
        # lanes — say so instead of printing nothing.
        report["note"] = (
            f"no /device:TPU plane (planes: {[p.name for p in xs.planes]}); "
            "capture on real TPU for the per-op table"
        )
        if not quiet:
            print(f"no /device:TPU plane in {path} (planes: "
                  f"{[p.name for p in xs.planes]}); capture on real TPU for "
                  "the per-op table")
        return report
    for plane in tpu_planes:
        emeta = {m.id: m.name for m in plane.event_metadata.values()}
        plane_rep: dict = {"name": plane.name, "lanes": {}}
        for line in plane.lines:
            if line.name not in ("XLA Ops", "Steps"):
                continue
            if line.name == "Steps":
                agg: collections.Counter = collections.Counter()
                for e in line.events:
                    agg[emeta[e.metadata_id]] += e.duration_ps
                total_ms = sum(agg.values()) / 1e9
                plane_rep["lanes"]["Steps"] = {
                    "steps": {
                        name: round(ps / 1e9, 6)
                        for name, ps in sorted(agg.items())
                    }
                }
                if not quiet:
                    print(f"\n== {plane.name} / {line.name}: "
                          f"{total_ms:.1f} ms total "
                          f"({len(line.events)} events)")
                    for name, ps in sorted(agg.items()):
                        print(f"  step {name}: {ps / 1e9:.2f} ms")
                continue
            # One events materialization + one aggregation per lane:
            # lane_report owns the Counter walk, the human table reads
            # its top_ops back out (real traces carry millions of spans).
            events = [
                (emeta[e.metadata_id], e.offset_ps,
                 e.offset_ps + e.duration_ps)
                for e in line.events
            ]
            rep = lane_report(events, top_n)
            plane_rep["lanes"]["XLA Ops"] = rep
            if not quiet:
                print(f"\n== {plane.name} / {line.name}: "
                      f"{rep['total_ms']:.1f} ms total "
                      f"({rep['n_events']} events)")
                print(f"  {'ms/step':>8s} {'count':>6s}  op")
                for row in rep["top_ops"]:
                    print(f"  {row['ms_per_step']:8.2f} "
                          f"{row['count']:6d}  {row['op'][:120]}")
                overlap_summary(line, emeta)
                decode_summary(line, emeta)
        report["planes"].append(plane_rep)
    return report


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("root", nargs="?", default="/tmp/jax_trace",
                    help="trace dir (latest *.xplane.pb under it is read)")
    ap.add_argument("top_n", nargs="?", type=int, default=20)
    ap.add_argument(
        "--json", dest="json_out", default=None, metavar="PATH",
        help="write the machine-readable report here ('-' = stdout, "
        "suppressing the human tables) — the diffable artifact for "
        "cross-PR overlap comparisons",
    )
    args = ap.parse_args(argv)
    report = analyze(args.root, args.top_n, quiet=args.json_out == "-")
    if args.json_out == "-":
        print(json.dumps(report, indent=1))
    elif args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(report, fh, indent=1)
        print(f"wrote JSON report to {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
