#!/usr/bin/env python
"""Summarize a jax.profiler TPU trace: top XLA ops by device time.

Pairs with the capture flow (BASELINE.md backlog, VERDICT r1 item 2):

    python - <<'PY'
    import jax
    ... warm up trainer ...
    jax.profiler.start_trace("/tmp/rn50_trace")
    ... N steps + device_get ...
    jax.profiler.stop_trace()
    PY
    python tools/trace_analyze.py /tmp/rn50_trace [top_n]

No tensorboard needed: the .xplane.pb is parsed with the protobuf module
that ships inside tensorflow (tensorflow.tsl.profiler.protobuf).
"""

from __future__ import annotations

import collections
import glob
import os
import sys


def find_xplane(root: str) -> str:
    hits = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no .xplane.pb under {root}")
    return hits[-1]  # latest capture


def main() -> int:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/jax_trace"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    path = find_xplane(root)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())

    tpu_planes = [
        p for p in xs.planes if p.name.startswith("/device:TPU")
    ]
    if not tpu_planes:
        # CPU-sim traces carry host thread lines, not per-op device
        # lanes — say so instead of printing nothing.
        print(
            f"no /device:TPU plane in {path} (planes: "
            f"{[p.name for p in xs.planes]}); capture on real TPU for "
            "the per-op table"
        )
        return 0
    for plane in tpu_planes:
        emeta = {m.id: m.name for m in plane.event_metadata.values()}
        for line in plane.lines:
            if line.name not in ("XLA Ops", "Steps"):
                continue
            agg: collections.Counter = collections.Counter()
            n_events: collections.Counter = collections.Counter()
            for e in line.events:
                agg[emeta[e.metadata_id]] += e.duration_ps
                n_events[emeta[e.metadata_id]] += 1
            total_ms = sum(agg.values()) / 1e9
            n_steps = len(line.events) if line.name == "Steps" else max(
                n_events.values(), default=1
            )
            print(f"\n== {plane.name} / {line.name}: {total_ms:.1f} ms total "
                  f"({len(line.events)} events)")
            if line.name == "Steps":
                for name, ps in sorted(agg.items()):
                    print(f"  step {name}: {ps / 1e9:.2f} ms")
                continue
            print(f"  {'ms/step':>8s} {'count':>6s}  op")
            for name, ps in agg.most_common(top_n):
                print(
                    f"  {ps / 1e9 / n_steps:8.2f} {n_events[name]:6d}  {name[:120]}"
                )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
