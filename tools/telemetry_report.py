#!/usr/bin/env python
"""Render a run's telemetry JSONL into percentile tables.

Input is the ``telemetry.jsonl`` a Trainer run (or any
``telemetry.jsonl_record`` producer) writes next to ``metrics.jsonl``:
``{"event": "telemetry", ...}`` registry snapshots interleaved with
``{"event": "timeline", ...}`` phase records. The LAST telemetry record
is cumulative, so the report reads it alone for totals and recomputes
any quantile straight from the raw log2 bucket counts it carries — no
re-observation, merge-safe across processes that share the bucket
ladder.

    python tools/telemetry_report.py <workdir>/<name>/telemetry.jsonl
    python tools/telemetry_report.py run/telemetry.jsonl --json report.json
    python tools/telemetry_report.py --diff run_a/telemetry.jsonl \
                                            run_b/telemetry.jsonl

The ``--json`` output is the machine-readable form a BENCH_TABLE row's
evidence can cite (percentiles per histogram, final counters/gauges,
timeline phase totals).

``--diff`` renders the A→B percentile-delta table over two runs' JSONLs:
every quantile is recomputed from each side's serialized bucket counts
(merge-safe, no re-observation — the shared log2 ladder is what makes
the subtraction meaningful), so "did this PR move TTFT p99" is one
command over two run dirs.
"""

from __future__ import annotations

import argparse
import json
import sys

#: Percentiles the tables render (quantiles are recomputed from buckets,
#: so adding one here needs no new data).
PERCENTILES = (50, 90, 95, 99)


def bucket_quantile(buckets: dict[str, int], count: int, q: float) -> float:
    """Quantile from a snapshot's CUMULATIVE bucket map (the
    ``telemetry.metrics.Histogram.quantile`` estimator, reconstructed
    from serialized state): linear interpolation inside the containing
    bucket, +Inf clamped to the last finite bound."""
    bounds = sorted(float(k) for k in buckets if k != "+Inf")
    if count <= 0 or not bounds:
        return 0.0
    target = q * count
    prev_cum = 0
    for i, b in enumerate(bounds):
        cum = buckets[_key(buckets, b)]
        if cum >= target and cum > prev_cum:
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + (b - lo) * min(max(frac, 0.0), 1.0)
        prev_cum = cum
    return bounds[-1]


def _key(buckets: dict[str, int], bound: float) -> str:
    """Map a parsed float bound back to its serialized dict key."""
    for k in buckets:
        if k != "+Inf" and float(k) == bound:
            return k
    raise KeyError(bound)


def load(path: str) -> dict:
    """Parse the JSONL; returns {"final": last snapshot metrics,
    "snapshots": n, "timeline": {name: {count, total_s}}}."""
    final: dict = {}
    n_snapshots = 0
    timeline: dict[str, dict[str, float]] = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("event") == "telemetry":
                final = rec.get("metrics", {})
                n_snapshots += 1
            elif rec.get("event") == "timeline":
                t = timeline.setdefault(
                    rec.get("name", "?"), {"count": 0, "total_s": 0.0}
                )
                t["count"] += 1
                t["total_s"] += float(rec.get("dur_s", 0.0))
    if not final:
        raise ValueError(
            f"{path}: no telemetry snapshot records "
            '({"event": "telemetry", ...} lines)'
        )
    return {"final": final, "snapshots": n_snapshots, "timeline": timeline}


def report(data: dict) -> dict:
    """The machine-readable report (the ``--json`` payload)."""
    hists, scalars = [], {}
    for name, v in sorted(data["final"].items()):
        if isinstance(v, dict) and v.get("type") == "histogram":
            count = int(v.get("count", 0))
            row = {
                "name": name,
                "count": count,
                "sum_s": round(float(v.get("sum", 0.0)), 6),
                "mean_s": round(float(v.get("sum", 0.0)) / count, 6)
                if count
                else 0.0,
            }
            for p in PERCENTILES:
                row[f"p{p}_s"] = round(
                    bucket_quantile(v.get("buckets", {}), count, p / 100.0), 6
                )
            hists.append(row)
        else:
            scalars[name] = v
    return {
        "snapshots": data["snapshots"],
        "histograms": hists,
        "scalars": scalars,
        "timeline": {
            name: {"count": int(t["count"]), "total_s": round(t["total_s"], 6)}
            for name, t in sorted(data["timeline"].items())
        },
    }


def render(rep: dict, out=None) -> None:
    out = out if out is not None else sys.stdout
    print(f"telemetry report ({rep['snapshots']} snapshot(s))", file=out)
    if rep["histograms"]:
        cols = ["count", "mean_s"] + [f"p{p}_s" for p in PERCENTILES]
        width = max(len(h["name"]) for h in rep["histograms"])
        print(
            f"\n  {'histogram':<{width}s} "
            + " ".join(f"{c:>12s}" for c in cols),
            file=out,
        )
        for h in rep["histograms"]:
            print(
                f"  {h['name']:<{width}s} "
                + " ".join(
                    f"{h[c]:12d}" if c == "count" else f"{h[c]:12.6f}"
                    for c in cols
                ),
                file=out,
            )
    if rep["scalars"]:
        print("\n  counters / gauges:", file=out)
        width = max(len(k) for k in rep["scalars"])
        for k, v in rep["scalars"].items():
            print(f"  {k:<{width}s} {v:g}", file=out)
    if rep["timeline"]:
        print("\n  timeline phases:", file=out)
        width = max(len(k) for k in rep["timeline"])
        for k, t in rep["timeline"].items():
            print(
                f"  {k:<{width}s} {t['count']:6d} events "
                f"{t['total_s']:10.6f} s total",
                file=out,
            )


def diff_report(rep_a: dict, rep_b: dict) -> dict:
    """The percentile-delta payload over two run reports: per histogram
    present on either side, both rows plus ``delta`` (B minus A; None
    when a side is missing); scalars likewise. Deterministic for fixed
    inputs — golden-tested (tests/golden/telemetry_report_diff.json)."""
    a_h = {h["name"]: h for h in rep_a["histograms"]}
    b_h = {h["name"]: h for h in rep_b["histograms"]}
    hists = []
    cols = ["count", "mean_s"] + [f"p{p}_s" for p in PERCENTILES]
    for name in sorted(a_h.keys() | b_h.keys()):
        ha, hb = a_h.get(name), b_h.get(name)
        delta = (
            {c: round(hb[c] - ha[c], 6) for c in cols}
            if ha is not None and hb is not None
            else None
        )
        hists.append({"name": name, "a": ha, "b": hb, "delta": delta})
    scalars = {}
    sa, sb = rep_a["scalars"], rep_b["scalars"]
    for name in sorted(sa.keys() | sb.keys()):
        va, vb = sa.get(name), sb.get(name)
        scalars[name] = {
            "a": va,
            "b": vb,
            "delta": round(vb - va, 6)
            if isinstance(va, (int, float)) and isinstance(vb, (int, float))
            else None,
        }
    return {"histograms": hists, "scalars": scalars}


def render_diff(rep: dict, out=None) -> None:
    out = out if out is not None else sys.stdout
    print("telemetry diff (B - A)", file=out)
    rows = rep["histograms"]
    if rows:
        width = max(len(h["name"]) for h in rows)
        cols = ["count"] + [f"p{p}_s" for p in PERCENTILES]
        print(
            f"\n  {'histogram':<{width}s} "
            + " ".join(f"{'d_' + c:>12s}" for c in cols),
            file=out,
        )
        for h in rows:
            if h["delta"] is None:
                side = "A only" if h["b"] is None else "B only"
                print(f"  {h['name']:<{width}s} ({side})", file=out)
                continue
            print(
                f"  {h['name']:<{width}s} "
                + " ".join(
                    f"{h['delta'][c]:+12d}" if c == "count"
                    else f"{h['delta'][c]:+12.6f}"
                    for c in cols
                ),
                file=out,
            )
    changed = {
        k: v for k, v in rep["scalars"].items()
        if v["delta"] not in (None, 0, 0.0)
        or v["a"] is None or v["b"] is None
    }
    if changed:
        print("\n  counters / gauges (changed):", file=out)
        width = max(len(k) for k in changed)
        for k, v in changed.items():
            if v["a"] is None or v["b"] is None:
                side = "A only" if v["b"] is None else "B only"
                val = v["a"] if v["b"] is None else v["b"]
                print(f"  {k:<{width}s} {val:g} ({side})", file=out)
                continue
            print(
                f"  {k:<{width}s} {v['a']:g} -> {v['b']:g} "
                f"({v['delta']:+g})",
                file=out,
            )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "path", nargs="?", default=None, help="telemetry.jsonl to render"
    )
    ap.add_argument(
        "--diff", nargs=2, metavar=("A", "B"), default=None,
        help="render the A→B percentile-delta table over two JSONLs",
    )
    ap.add_argument(
        "--json", dest="json_out", default=None, metavar="PATH",
        help="also write the machine-readable report ('-' = stdout only)",
    )
    args = ap.parse_args(argv)
    if args.diff is not None and args.path is not None:
        ap.error("pass either a telemetry.jsonl path or --diff A B, not both")
    if args.diff is not None:
        rep = diff_report(
            report(load(args.diff[0])), report(load(args.diff[1]))
        )
        renderer = render_diff
    elif args.path is not None:
        rep = report(load(args.path))
        renderer = render
    else:
        ap.error("pass a telemetry.jsonl path or --diff A B")
    if args.json_out == "-":
        print(json.dumps(rep, indent=1))
        return 0
    renderer(rep)
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(rep, fh, indent=1)
        print(f"\nwrote JSON report to {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
