#!/usr/bin/env python
"""graft-lint CLI: enforce the repo's performance invariants statically.

Lints every registered recipe's train step (trace-only: jaxpr + lowered
StableHLO, no XLA compile), the ``schedule:`` program family (every
overlap recipe's train step re-checked against the expectations DERIVED
from its declared ``parallel/schedule.py`` OverlapSchedule — ISSUE 13),
the serving decode step, and the traced modules' Python source, then
emits a JSON report and exits non-zero on any ``severity:error``
finding.  CPU-sim safe: forces JAX_PLATFORMS=cpu with 8 virtual
devices, the same harness as the test suite.

    python tools/graft_lint.py --all-recipes            # the CI gate
    python tools/graft_lint.py --recipe gpt2_medium_tp_overlap
    python tools/graft_lint.py --all-recipes --json report.json
    python tools/graft_lint.py --all-recipes --budget-mb 256
    python tools/graft_lint.py --all-recipes --save-census census.json
    python tools/graft_lint.py --all-recipes --against census.json

Passes and their error conditions are cataloged in
docs/static_analysis.md; per-recipe shrink shapes live in
``analysis.runner.RECIPE_OVERRIDES`` (a recipe without an entry is itself
a lint error — the gate must never trace production shapes on the sim).

``--save-census`` / ``--against`` persist and diff the per-recipe
collective censuses: the promoted form of "this refactor didn't change
the step's communication".  A diff is reported as a warning (visible,
not blocking) because census changes are sometimes the point of a PR —
refresh the baseline in the same commit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Platform pins BEFORE jax imports (the conftest.py discipline): the
# environment may pin JAX_PLATFORMS to a real TPU plugin.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _apply_census_diff(reports, against_path):
    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        census_diff,
    )

    with open(against_path) as fh:
        baseline = json.load(fh)
    for rep in reports:
        rows = rep.meta.get("collective_census")
        if rows is None or rep.program not in baseline:
            continue
        old = [_record_from_dict(d) for d in baseline[rep.program]]
        new = [_record_from_dict(d) for d in rows]
        diff = census_diff(old, new)
        for kind in ("added", "removed"):
            for entry in diff[kind]:
                rep.add(
                    "collective_census", "warning", f"census-{kind}",
                    f"{entry['count']}x {entry['primitive']} "
                    f"{entry['shapes']} on axes {entry['axes']} "
                    f"{kind} vs baseline",
                    **entry,
                )


def _record_from_dict(d):
    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        CollectiveRecord,
    )

    return CollectiveRecord(
        primitive=d["primitive"],
        axes=tuple(d["axes"]),
        shapes=tuple(tuple(s) for s in d["shapes"]),
        dtype=d["dtype"],
        bytes_per_call=d["bytes_per_call"],
        trip_count=d["trip_count"],
        path=tuple(d["path"]),
    )


#: The pass families --only selects from (argparse refuses anything
#: else — a typo'd pass name must fail loudly, not lint nothing).
_FAMILIES = (
    "recipes", "serving", "reshard", "hygiene", "robustness", "concurrency",
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--all-recipes", action="store_true",
        help="lint every registered recipe (plus serving + hygiene + "
        "robustness)",
    )
    ap.add_argument(
        "--recipe", action="append", default=[],
        help="lint one recipe (repeatable)",
    )
    ap.add_argument(
        "--no-serving", action="store_true",
        help="skip the serving decode-step lint",
    )
    ap.add_argument(
        "--no-reshard", action="store_true",
        help="skip the redistribution executor (reshard:*) lint",
    )
    ap.add_argument(
        "--no-hygiene", action="store_true",
        help="skip the AST hygiene lint",
    )
    ap.add_argument(
        "--no-robustness", action="store_true",
        help="skip the failure-semantics robustness lint",
    )
    ap.add_argument(
        "--no-concurrency", action="store_true",
        help="skip the lock-discipline concurrency lint",
    )
    ap.add_argument(
        "--only", action="append", default=[], metavar="PASS",
        choices=sorted(_FAMILIES),
        help="run ONLY the named pass families (repeatable; one of: "
        + ", ".join(sorted(_FAMILIES))
        + "). Unknown names are refused. 'recipes' still needs "
        "--all-recipes or --recipe.",
    )
    ap.add_argument(
        "--budget-mb", type=float, default=None,
        help="materialization budget per intermediate, in MiB (error "
        "above; default: census only)",
    )
    ap.add_argument("--json", help="write the full JSON report here")
    ap.add_argument(
        "--save-census", help="write per-program collective censuses here"
    )
    ap.add_argument(
        "--against", help="diff censuses against a --save-census file"
    )
    ap.add_argument(
        "--workdir", default="/tmp/graft_lint",
        help="scratch workdir for recipe construction",
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="only print failing programs and the final summary",
    )
    args = ap.parse_args(argv)
    only = set(args.only)
    if only:
        if (
            args.no_serving or args.no_reshard or args.no_hygiene
            or args.no_robustness or args.no_concurrency
        ):
            ap.error("--only cannot be combined with --no-* flags")
        if "recipes" in only and not (args.all_recipes or args.recipe):
            ap.error("--only recipes needs --all-recipes or --recipe NAME")
    elif not args.all_recipes and not args.recipe:
        ap.error("pass --all-recipes or at least one --recipe NAME")

    run_recipes = "recipes" in only if only else True

    def _family(name: str, no_flag: bool) -> bool:
        return (name in only) if only else not no_flag

    from frl_distributed_ml_scaffold_tpu.analysis.runner import lint_all

    budget = (
        int(args.budget_mb * 1024 * 1024)
        if args.budget_mb is not None
        else None
    )

    def progress(rep):
        if not args.quiet or not rep.ok:
            for line in rep.summary_lines():
                print(line, flush=True)

    reports = lint_all(
        recipes=(
            (None if args.all_recipes else args.recipe)
            if run_recipes
            else []
        ),
        serving=_family("serving", args.no_serving),
        reshard=_family("reshard", args.no_reshard),
        hygiene=_family("hygiene", args.no_hygiene),
        robustness=_family("robustness", args.no_robustness),
        concurrency=_family("concurrency", args.no_concurrency),
        workdir=args.workdir,
        budget_bytes=budget,
        on_report=progress if args.against is None else None,
    )
    if args.against:
        _apply_census_diff(reports, args.against)
        for rep in reports:
            progress(rep)

    if args.save_census:
        censuses = {
            r.program: r.meta["collective_census"]
            for r in reports
            if "collective_census" in r.meta
        }
        with open(args.save_census, "w") as fh:
            json.dump(censuses, fh, indent=1)
        print(f"wrote censuses for {len(censuses)} programs to "
              f"{args.save_census}")
    if args.json:
        with open(args.json, "w") as fh:
            json.dump([r.to_dict() for r in reports], fh, indent=1)
        print(f"wrote JSON report to {args.json}")

    n_err = sum(len(r.errors()) for r in reports)
    n_warn = sum(len(r.warnings()) for r in reports)
    n_fail = sum(1 for r in reports if not r.ok)
    print(
        f"graft-lint: {len(reports)} programs, {n_fail} failing, "
        f"{n_err} error(s), {n_warn} warning(s)"
    )
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
