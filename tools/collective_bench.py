#!/usr/bin/env python
"""Collective micro-benchmark over the device mesh (SURVEY C2 / §5 comms).

Times each collective in the ``dist`` façade (allreduce, all_gather,
reduce_scatter, ppermute, all_to_all) at a sweep of payload sizes, one
JSONL line per (op, bytes): achieved algorithmic bandwidth per chip. On a
pod this measures ICI (and DCN when the mesh spans slices); on the CPU sim
the numbers are meaningless but the harness and every lowering still run —
which is what the CI test asserts.

    python tools/collective_bench.py                    # whole-mesh axis
    python tools/collective_bench.py --axis data --mb 1 4 16

Algorithmic bandwidth convention (the NCCL one): busbw = bytes x
2(n-1)/n / t for allreduce, bytes x (n-1)/n / t for all_gather and
reduce_scatter, bytes / t for ppermute and (per-chip payload) all_to_all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--axis", default="data",
                    help="mesh axis to benchmark over")
    ap.add_argument("--devices", type=int, default=0,
                    help="mesh size (0 = all visible devices)")
    ap.add_argument("--mb", type=float, nargs="*", default=[1, 8, 64],
                    help="payload megabytes per chip")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from frl_distributed_ml_scaffold_tpu.dist import collectives as C

    devs = jax.devices()[: args.devices or None]
    n = len(devs)
    # Topology-aware ordering (mesh-adjacent == ICI-adjacent) — the raw
    # enumeration order would time multi-hop routes and under-report.
    from jax.experimental import mesh_utils

    try:
        dev_array = mesh_utils.create_device_mesh((n,), devices=devs)
    except (ValueError, AssertionError):  # e.g. CPU sim subsets
        dev_array = np.array(devs)
    mesh = Mesh(dev_array, (args.axis,))
    axis = args.axis
    primary = jax.process_index() == 0

    def emit(rec):
        if primary:
            print(json.dumps(rec), flush=True)

    def timed(fn, x):
        out0 = fn(x)  # compile
        # Settle on the compile call's OWN output — syncing on an unrelated
        # array would not order after fn(x)'s execution, letting leftover
        # compile-call work bleed into the first timed iteration. Must be
        # block_until_ready, not device_get: collective outputs sharded
        # P(axis) across a multi-host pod are not fully addressable, so
        # any host fetch raises; blocking needs no transfer. (The relay's
        # slow block_until_ready RPC is a single-chip quirk; this tool
        # only ever times multi-device meshes.)
        jax.block_until_ready(out0)
        t0 = time.perf_counter()
        out = None
        for _ in range(args.iters):
            out = fn(x)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.iters

    OPS = {
        # name: (shard_map fn, out_specs, busbw multiplier as f(n))
        "all_reduce": (
            lambda v: C.all_reduce(v, axis), P(),
            lambda n: 2 * (n - 1) / n,
        ),
        "all_gather": (
            lambda v: C.all_gather(v, axis), P(), lambda n: (n - 1) / n,
        ),
        "reduce_scatter": (
            lambda v: C.reduce_scatter(v, axis), P(axis),
            lambda n: (n - 1) / n,
        ),
        "permute": (
            lambda v: C.permute(
                v, axis, perm=[(i, (i + 1) % n) for i in range(n)]
            ),
            P(axis),
            lambda n: 1.0,
        ),
        "all_to_all": (
            lambda v: C.all_to_all(v, axis, split_axis=0, concat_axis=0),
            P(axis),
            lambda n: (n - 1) / n,
        ),
    }

    for mb in args.mb:
        per_chip = int(mb * 2**20 / 4)  # fp32 elements per chip
        per_chip = max(n, per_chip - per_chip % n)  # divisible for a2a
        # Assemble from per-process local data (multi-host pods cannot
        # device_put onto non-addressable devices) — the same pattern the
        # data pipeline uses.
        sharding = NamedSharding(mesh, P(axis))
        n_local = per_chip * n // jax.process_count()
        local = np.arange(n_local, dtype=np.float32)
        sharded = jax.make_array_from_process_local_data(
            sharding, local, (per_chip * n,)
        )
        for name, (fn, out_specs, mult) in OPS.items():
            from frl_distributed_ml_scaffold_tpu.dist.mesh import (
                shard_map_compat,
            )

            smfn = jax.jit(
                shard_map_compat(
                    fn, mesh=mesh, in_specs=P(axis), out_specs=out_specs,
                )
            )
            try:
                dt = timed(smfn, sharded)
                bytes_per_chip = per_chip * 4
                busbw = bytes_per_chip * mult(n) / dt
                emit({
                    "op": name, "axis": axis, "n": n,
                    "mb_per_chip": round(bytes_per_chip / 2**20, 2),
                    "time_us": round(dt * 1e6, 1),
                    "busbw_gbps": round(busbw / 1e9, 2),
                })
            except Exception as e:
                emit({
                    "op": name, "axis": axis, "n": n,
                    "error": str(e)[:160],
                })
    return 0


if __name__ == "__main__":
    sys.exit(main())
