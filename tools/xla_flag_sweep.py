#!/usr/bin/env python
"""XLA compiler-flag sweep for the RN50 headline candidate.

XLA_FLAGS are frozen when the backend initializes, so each flag set runs
``tools/perf_sweep.py rn50_headline`` in its own bounded subprocess; an
unknown flag (XLA hard-errors on those) or a compile hang is recorded as
an error line, not a sweep abort. Candidate list: the public single-chip
TPU tuning surface — scoped-VMEM budget (bigger fusions for the
bandwidth-bound BN-backward passes that dominate the RN50 step, see
BASELINE.md trace analysis) and the memory-bound-loop / prefetch knobs.

    python tools/xla_flag_sweep.py            # full sweep
    python tools/xla_flag_sweep.py 0 2 5      # sweep indices
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TIMEOUT_S = int(os.environ.get("FRL_SWEEP_TIMEOUT_S", "420"))

# Each entry: extra XLA_FLAGS appended to the environment's own.
CANDIDATES: list[str] = [
    "",  # baseline (re-measured in the same session for a fair delta)
    "--xla_tpu_scoped_vmem_limit_kib=49152",
    "--xla_tpu_scoped_vmem_limit_kib=65536",
    "--xla_tpu_scoped_vmem_limit_kib=98304",
    # Memory-space-assignment prefetch aggressiveness (async HBM->VMEM
    # copies overlapping compute; relevant when fusions are bandwidth-bound).
    "--xla_tpu_async_copy_bandwidth_scaling_factor=2.0",
    "--xla_vf_vmem_max_overlap_to_mem_size_async_copy_ratio=10",
    # Loop-invariant code motion size budget (hoists more out of loops).
    "--xla_tpu_licm_size_inflation_ratio=2.0",
    # Combined best-of candidates get appended by hand after a first pass.
]


def run_one(flags: str) -> dict:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flags).strip()
    # No compile-cache handling needed: perf_sweep never enables the
    # persistent cache, so every flag set compiles fresh.
    t0 = time.perf_counter()
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "perf_sweep.py"), "rn50_headline"],
            capture_output=True, text=True, timeout=TIMEOUT_S, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return {"flags": flags, "error": f"timeout after {TIMEOUT_S}s"}
    dt = time.perf_counter() - t0
    for line in r.stdout.splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("experiment") == "rn50_headline":
            rec["flags"] = flags
            rec["wall_s"] = round(dt, 1)
            return rec
    return {"flags": flags, "error": (r.stderr.strip()[-300:] or
                                      f"no result line (rc={r.returncode})")}


def main() -> int:
    idxs = [int(a) for a in sys.argv[1:]] or range(len(CANDIDATES))
    for i in idxs:
        flags = CANDIDATES[i]
        print(f"[{i}] {flags or '(baseline)'}", file=sys.stderr, flush=True)
        print(json.dumps(run_one(flags)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
