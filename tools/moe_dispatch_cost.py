#!/usr/bin/env python
"""MoE dispatch-cost comparison: einsum (one-hot GSEC) vs sort (ragged).

VERDICT r4 weak #5: the dispatch/combine einsums spend O(N*E*C*D) MACs
against mostly-zero one-hots, and no number existed for what that costs
versus a sort/ragged formulation at the audited shapes (N=4096, E=64).
This tool asks XLA's own cost model: jit the MoE block's train-mode
value+grad under each ``moe.dispatch`` and read ``cost_analysis()`` —
the same FLOP source bench.py's MFU uses — plus an analytic expert-FFN
FLOP count for scale.

    JAX_PLATFORMS=cpu python tools/moe_dispatch_cost.py

One JSONL row per (shape, dispatch) + a verdict row. Results recorded in
docs/perf_playbook.md "Dispatch FLOPs"; the einsum default stands or
falls on these numbers plus the on-chip step-time A/B (relay-gated).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def measure(b: int, t: int, d: int, e: int, k: int, dispatch: str) -> dict:
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig, MoEConfig
    from frl_distributed_ml_scaffold_tpu.models.moe import MoEMlp

    cfg = GPTConfig(
        hidden_dim=d, num_heads=4, seq_len=t,
        moe=MoEConfig(num_experts=e, top_k=k, dispatch=dispatch,
                      num_groups=1),
    )
    m = MoEMlp(cfg, jnp.bfloat16)
    x = jnp.zeros((b, t, d), jnp.bfloat16)
    variables = jax.eval_shape(lambda: m.init(jax.random.key(0), x, train=True))

    def loss_fn(v, xx):
        y, aux = m.apply(v, xx, train=True)
        return jnp.sum(y.astype(jnp.float32) ** 2) + aux

    grad = jax.grad(loss_fn)
    lowered = jax.jit(grad).lower(variables, x)
    cost = lowered.compile().cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost
    n = b * t
    capacity = max(1, int(cfg.moe.capacity_factor * n * k / e))
    hidden = d * cfg.mlp_ratio
    # Expert FFN MACs (fwd): E*C*D*H twice (wi, wo); x3 for fwd+bwd; x2
    # FLOPs/MAC. Exchange einsum MACs (fwd): N*E*C*D for each of
    # dispatch/combine; x3 for fwd+bwd.
    ffn_flops = 3 * 2 * 2 * e * capacity * d * hidden
    exchange_einsum_flops = 3 * 2 * 2 * n * e * capacity * d
    return {
        "shape": f"N={n} E={e} C={capacity} D={d} k={k}",
        "dispatch": dispatch,
        "xla_total_flops": float(cost.get("flops", -1)),
        "analytic_expert_ffn_flops": float(ffn_flops),
        "analytic_exchange_einsum_flops": float(exchange_einsum_flops),
    }


def main() -> int:
    # Pin the CPU backend UNCONDITIONALLY: the environment exports
    # JAX_PLATFORMS=axon and the sitecustomize pins it again at the
    # jax.config level, so both must be overwritten before backend init.
    # XLA's cost model is platform-independent for FLOP counting purposes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    # The audited shapes (perf_playbook "MoE dispatch memory at real
    # shapes"): gpt2_moe protocol point N=4096, E=64 — plus a small
    # sanity shape.
    shapes = [
        (4, 256, 256, 16, 2),   # sanity
        (4, 1024, 1024, 64, 2), # audited: N=4096, E=64, D=1024
    ]
    rows = []
    for b, t, d, e, k in shapes:
        for dispatch in ("einsum", "sort"):
            r = measure(b, t, d, e, k, dispatch)
            rows.append(r)
            print(json.dumps(r), flush=True)
    for i in range(0, len(rows), 2):
        ein, srt = rows[i], rows[i + 1]
        if ein["xla_total_flops"] > 0 and srt["xla_total_flops"] > 0:
            print(json.dumps({
                "mode": "verdict",
                "shape": ein["shape"],
                "einsum_over_sort_flops": round(
                    ein["xla_total_flops"] / srt["xla_total_flops"], 3
                ),
                "exchange_share_of_einsum_total": round(
                    ein["analytic_exchange_einsum_flops"]
                    / ein["xla_total_flops"], 3
                ),
            }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
