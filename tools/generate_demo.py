#!/usr/bin/env python
"""Generate tokens from a GPT config — the decode-path demo/smoke.

    python tools/generate_demo.py gpt2_medium_zero1 \
        [--restore <workdir>] [--max-new 32] [--temperature 0.8] [--top-k 40] \
        [overrides...]

Without --restore the params are random init (useful as an on-chip decode
smoke: it exercises prefill + cached stepping at real model shapes). With
--restore it loads the latest Orbax checkpoint the trainer wrote.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("overrides", nargs="*", default=[])
    ap.add_argument("--restore", default=None, help="trainer workdir to load")
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-k", type=int, default=40)
    ap.add_argument("--top-p", type=float, default=0.0,
                    help="nucleus sampling mass (0 = off)")
    ap.add_argument("--beams", type=int, default=0,
                    help="beam-search width (0 = sample instead)")
    ap.add_argument("--length-penalty", type=float, default=0.0,
                    help="beam re-rank: score / len**alpha (0 = raw sum)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, get_config
    from frl_distributed_ml_scaffold_tpu.models import create_model
    from frl_distributed_ml_scaffold_tpu.models.generation import generate
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    cfg = apply_overrides(get_config(args.config), list(args.overrides))
    if getattr(cfg.model, "family", None) != "gpt":
        raise SystemExit(f"{args.config} is not a GPT config")
    model = create_model(cfg.model, get_policy(cfg.precision))

    import jax.numpy as jnp

    rng = jax.random.key(args.seed)
    prompt = jax.random.randint(
        rng, (args.batch, args.prompt_len), 0, cfg.model.vocab_size
    )
    if args.restore:
        from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

        cfg = apply_overrides(
            cfg, [f"workdir={args.restore}", "checkpoint.enabled=true"]
        )
        trainer = Trainer(cfg)
        state = trainer.checkpointer.restore_or_init(trainer)
        params = state.params
        print(f"[generate_demo] restored step {int(jax.device_get(state.step))}")
    else:
        params = model.init({"params": rng}, prompt, train=False)["params"]
        print("[generate_demo] random-init params (no --restore given)")

    t0 = time.perf_counter()
    if args.beams > 0:
        print("[generate_demo] beam search is deterministic: "
              "--temperature/--top-k/--top-p/--seed are ignored")
        from frl_distributed_ml_scaffold_tpu.models.generation import (
            beam_search,
        )

        out, scores = beam_search(
            model, params, prompt,
            max_new_tokens=args.max_new, num_beams=args.beams,
            length_penalty=args.length_penalty,
        )
        print(f"[generate_demo] beam scores: "
              f"{[round(float(s), 2) for s in jax.device_get(scores)]}")
    else:
        out = generate(
            model,
            params,
            prompt,
            max_new_tokens=args.max_new,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            rng=jax.random.key(args.seed + 1),
        )
    out = jax.device_get(out)
    dt = time.perf_counter() - t0
    print(f"[generate_demo] {args.max_new} tokens x {args.batch} seqs "
          f"in {dt:.2f}s ({args.batch * args.max_new / dt:.1f} tok/s incl. compile)")
    # Second call of the SAME decode path hits the compile cache:
    # steady-state throughput (and for beam mode, the printed rows must
    # remain the beam result — never overwrite with sampled output).
    t0 = time.perf_counter()
    if args.beams > 0:
        out, _ = beam_search(
            model, params, prompt,
            max_new_tokens=args.max_new, num_beams=args.beams,
            length_penalty=args.length_penalty,
        )
        out = jax.device_get(out)
    else:
        out = jax.device_get(
            generate(
                model, params, prompt,
                max_new_tokens=args.max_new, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p,
                rng=jax.random.key(args.seed + 2),
            )
        )
    dt = time.perf_counter() - t0
    print(f"[generate_demo] warm: {args.batch * args.max_new / dt:.1f} tok/s "
          f"({dt / args.max_new * 1e3:.1f} ms/token step)")
    for row in out:
        print("  ", row.tolist())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
