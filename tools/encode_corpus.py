#!/usr/bin/env python
"""Raw text → token-bin corpus producer for the LM loader (SURVEY C16).

The LM loader reads nanoGPT-style flat token binaries
(``{split}.bin`` + sidecar — data/lm.py ``write_token_bin``); this is the
CLI that materializes them from text:

    # Hugging Face tokenizer from a LOCAL checkpoint/tokenizer dir (this
    # image has no network; any dir transformers can load offline works):
    python tools/encode_corpus.py <out_dir> a.txt b.txt \
        --tokenizer /path/to/gpt2_dir --split train

    # Zero-dependency byte-level fallback (vocab 256 = raw UTF-8 bytes —
    # the classic char/byte-LM setup; pairs with model.vocab_size=256):
    python tools/encode_corpus.py <out_dir> corpus.txt --byte-level

Files are concatenated in argument order with ``--eot-id`` (tokenizer's
eos by default; 0 for byte-level) between documents, the convention LM
samplers rely on to avoid cross-document attention windows carrying
meaning. Emits one JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def encode_files(paths, args) -> tuple[np.ndarray, int]:
    """Returns (token stream, vocab_size)."""
    if args.byte_level:
        eot = 0 if args.eot_id is None else args.eot_id
        if not 0 <= eot < 256:
            # Out-of-range ids would either wrap in the uint16 separator
            # array or fail late in write_token_bin after all files are
            # read; fail fast against the byte vocab, mirroring the
            # tokenizer path's check below.
            raise SystemExit(
                f"--eot-id {eot} out of byte-level vocab range [0, 256)"
            )
        chunks = []
        for p in paths:
            with open(p, "rb") as fh:
                chunks.append(np.frombuffer(fh.read(), np.uint8).astype(np.uint16))
            chunks.append(np.array([eot], np.uint16))
        return np.concatenate(chunks), 256

    from transformers import AutoTokenizer  # host tooling only

    tok = AutoTokenizer.from_pretrained(args.tokenizer)
    eot = tok.eos_token_id if args.eot_id is None else args.eot_id
    if eot is None:
        raise SystemExit(
            "tokenizer has no eos token; pass --eot-id explicitly"
        )
    if not 0 <= eot < len(tok):
        raise SystemExit(
            f"--eot-id {eot} out of tokenizer vocab range [0, {len(tok)})"
        )
    chunks = []
    for p in paths:
        with open(p, encoding="utf-8") as fh:
            # No automatic special tokens: tokenizers that inject BOS/CLS/
            # SEP per encode would double up on the explicit eot separator
            # and scatter spurious marker ids through the stream.
            ids = tok.encode(fh.read(), add_special_tokens=False)
        chunks.append(np.asarray(ids, np.int64))
        chunks.append(np.array([eot], np.int64))
    return np.concatenate(chunks), int(len(tok))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("files", nargs="+")
    ap.add_argument("--split", default="train")
    ap.add_argument("--tokenizer", default=None,
                    help="local HF tokenizer dir/name (offline)")
    ap.add_argument("--byte-level", action="store_true",
                    help="raw UTF-8 bytes, vocab 256 (no tokenizer needed)")
    ap.add_argument("--eot-id", type=int, default=None,
                    help="document separator id (default: tokenizer eos; 0 for bytes)")
    args = ap.parse_args()
    if not args.byte_level and args.tokenizer is None:
        ap.error("pass --tokenizer <local dir> or --byte-level")

    from frl_distributed_ml_scaffold_tpu.data.lm import write_token_bin

    tokens, vocab = encode_files(args.files, args)
    path = os.path.join(args.out_dir, f"{args.split}.bin")
    write_token_bin(path, tokens, vocab_size=vocab)
    print(json.dumps({
        "split": args.split, "tokens": int(tokens.size),
        "vocab_size": vocab, "files": len(args.files), "path": path,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
