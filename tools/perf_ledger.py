#!/usr/bin/env python
"""Perf-attribution ledger: census-vs-measured roofline table + gate.

Joins graft-lint's ANALYTIC side (collective census bytes + jaxpr-counted
FLOPs per recipe — deterministic, trace-only, the SimpleFSDP
compile-artifact-accounting shape, arXiv 2411.00284) with the telemetry
layer's MEASURED side (step-time histograms from a tiny CPU-sim fit;
TTFT/TPOT from a tiny serve run) into one per-recipe attribution row:

- ``flops_per_step`` / ``collective_bytes_per_step`` / arithmetic
  intensity, and the roofline verdict (compute- vs comm-bound at the
  configured peaks);
- the recipe's DECLARED overlap schedule (parallel/schedule.py
  ``describe()`` — rows are per-schedule, not per-recipe: what the step
  declares about its gathers/scatters/lowp gates together with the
  census that declaration produces; "gspmd" for plain recipes);
- measured ``step_time_p50_s``, achieved FLOP/s, and MFU — so "where did
  the time go" has an analytic denominator next to every measured number.

With the on-chip bench relay down (BACKLOG R6-1/R7-1/R8-1), this is the
repo's regression gate: the analytic side is bit-deterministic on the
CPU sim, so ``--check`` against the committed baseline
(``PERF_LEDGER.json``) catches any change to a step's communication or
compute census — the promoted, blocking form of graft-lint's advisory
census diff. Measured columns are provenance (stamped when the baseline
was built) and are only re-compared under ``--measure-steps``, with a
wide tolerance, because CPU-sim wall time is load-dependent.

    python tools/perf_ledger.py --write PERF_LEDGER.json --measure-steps 6
    python tools/perf_ledger.py --check                  # the CI gate
    python tools/perf_ledger.py --check --measure-steps 6 --tol 3.0

Exit is nonzero when any baseline row's analytic fields drift, a
baseline recipe disappears, or (under ``--measure-steps``) a measured
step time leaves its tolerance band.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Platform pins BEFORE jax imports (the graft_lint.py / conftest.py
# discipline): the environment may pin JAX_PLATFORMS to a real TPU plugin.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

#: Default baseline location (committed at the repo root, next to
#: BASELINE.json / BENCH_TABLE.jsonl).
DEFAULT_BASELINE = os.path.join(_REPO, "PERF_LEDGER.json")

#: The committed tiny-recipe set: one replicated-DDP recipe (census is
#: empty at the jaxpr level — GSPMD owns its collectives), one
#: explicit-schedule recipe (the ppermute rings ARE the census), and the
#: composed fsdp x TP overlap schedule (ISSUE 13 — blockwise gathers AND
#: rings in one scan body). Small enough that --check stays inside the
#: lint tier's budget.
DEFAULT_RECIPES = (
    "mnist_mlp", "gpt2_medium_tp_overlap", "gpt2_medium_fsdp_tp_overlap",
)

SERVING_PROGRAM = "serving:decode_step"
PAGED_SERVING_PROGRAM = "serving:decode_step_paged"
VERIFY_SERVING_PROGRAM = "serving:verify_step_paged"
HANDOFF_PROGRAM = "serving:handoff"

#: MPMD pipeline per-stage rows (ISSUE 14): one row per stage program of
#: the tiny-twin MPMD recipe — census/FLOPs of the microbatch
#: fwd+bwd program, the analytic 1F1B bubble/peak-live model, and the
#: explicit boundary-transfer bytes the driver moves per microbatch
#: (which is the whole inter-stage communication story: stage programs
#: are census-pinned collective-free across stages by graft-lint).
MPMD_RECIPE = "gpt2_pipeline_mpmd"
MPMD_STAGE_PREFIX = "pipeline:stage"

#: Redistribution-service migration rows (ISSUE 15): one per lintable
#: same-mesh executor program class (reshard:* — census bytes ARE the
#: wire cost) plus the tree-level train→serve handoff plan over the
#: tiny-GPT twin (chunked cross-mesh — priced by the plan compiler's
#: cost model: bytes_moved vs the shard-delta lower bound, peak
#: scratch). Analytic-only; the measured arm is queued as BACKLOG R18-1
#: (perf_sweep reshard_train_to_serve).
REDISTRIBUTE_PREFIX = "redistribute:"

#: Analytic row fields --check compares EXACTLY. Everything else in a row
#: (intensity, roofline, measured) is either derived from these or
#: measured wall time. ``schedule`` makes the rows per-SCHEDULE (ISSUE
#: 13): each row carries its recipe's declared OverlapSchedule
#: descriptor, so a change to WHAT a recipe declares (axes, granularity,
#: prefetch, lowp) gates exactly like a change to the census the
#: declaration produces.
ANALYTIC_KEYS = (
    "flops_per_step",
    "collective_bytes_per_step",
    "collectives",
    "params_bytes",
    "chips",
    "schedule",
)


def peak_ici_bytes_per_chip_s() -> float:
    """Per-chip interconnect bandwidth for the roofline's comm leg —
    v5e ICI (~4.5e10 B/s per link direction x 2 links, a deliberately
    round planning number, not a datasheet quote), overridable via
    ``FRL_PEAK_ICI_BYTES_PER_CHIP`` when the mesh lands elsewhere."""
    return float(os.environ.get("FRL_PEAK_ICI_BYTES_PER_CHIP", 9e10))


def _tree_bytes(tree) -> int:
    import jax
    import numpy as np

    return int(
        sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(tree)
        )
    )


def _roofline(flops: int, comm_bytes: int, chips: int) -> dict:
    """Lower-bound times at the configured peaks and the resulting bound
    verdict. NOT compared by --check (env overrides move the peaks);
    recomputed at read time for the table."""
    from frl_distributed_ml_scaffold_tpu.utils.flops import (
        peak_flops_per_chip,
    )

    peak_f = peak_flops_per_chip()
    peak_b = peak_ici_bytes_per_chip_s()
    compute_s = flops / (chips * peak_f) if flops else 0.0
    comm_s = comm_bytes / (chips * peak_b) if comm_bytes else 0.0
    return {
        "compute_s_lower_bound": compute_s,
        "comm_s_lower_bound": comm_s,
        "bound": "compute" if compute_s >= comm_s else "comm",
    }


def analytic_recipe_row(name: str, workdir: str) -> dict:
    """The deterministic half of a recipe's row: jaxpr FLOPs + collective
    census of the (tiny-twin) train step, shapes via analysis.runner."""
    import jax

    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        census_summary,
        collective_census,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        _abstract_batch,
        _build_trainer,
    )
    from frl_distributed_ml_scaffold_tpu.utils.flops import jaxpr_flops

    from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
        schedule_from_config,
    )

    trainer = _build_trainer(name, workdir)
    batch = _abstract_batch(trainer)
    jaxpr = trainer._mesh_scoped(jax.make_jaxpr(trainer._train_step_fn))(
        trainer.state_shapes, batch
    )
    census = collective_census(jaxpr)
    flops = jaxpr_flops(jaxpr)
    comm = sum(r.total_bytes for r in census)
    chips = jax.device_count()
    # Rows are per-SCHEDULE (ISSUE 13): the declared OverlapSchedule
    # descriptor rides next to the census it is supposed to produce, and
    # --check gates both together. Recipes with no overlap declaration
    # record the GSPMD schedule explicitly.
    sched = schedule_from_config(trainer.cfg)
    return {
        "flops_per_step": flops,
        "collective_bytes_per_step": comm,
        "collectives": {
            prim: agg for prim, agg in sorted(census_summary(census).items())
        },
        "params_bytes": _tree_bytes(trainer.state_shapes.params),
        "chips": chips,
        "schedule": (
            sched.describe() if sched is not None
            else {"declared": "gspmd", "short": "gspmd"}
        ),
        "intensity_flops_per_byte": round(flops / max(comm, 1), 3),
        "roofline": _roofline(flops, comm, chips),
    }


def analytic_serving_row(
    paged: bool = False, verify: bool = False, handoff: bool = False,
) -> dict:
    """Same, for the serving decode step (the graft-lint program, shared
    via analysis.runner.build_decode_step_program). ``paged=True`` builds
    the ISSUE-10 block-table decode step instead
    (build_paged_decode_step_program — the paged engine's ONE compiled
    decode shape); ``verify=True`` builds the ISSUE-11 speculative
    verify step (build_verify_step_program — the [B, k+1] tile), whose
    row additionally carries the amortization twin: ``positions_per
    _invocation`` = k+1 query positions score against ONE pool read, so
    ``flops_per_position`` sits next to the decode row's whole-step
    FLOPs — the analytic face of serve_bench's measured
    accepted-per-verify / invocations-per-token columns."""
    import jax

    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        census_summary,
        collective_census,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        build_decode_step_program,
        build_handoff_program,
        build_paged_decode_step_program,
        build_verify_step_program,
    )
    from frl_distributed_ml_scaffold_tpu.utils.flops import jaxpr_flops

    if handoff:
        # The handoff SPLICE row (ISSUE 12): the analytic cost of moving
        # a finished prefill decode-side. The headline is what the row
        # PINS: ownership moves as one block-table row
        # (``splice_table_bytes`` — int32 per table slot), the program
        # writes only the private blocks that change owner
        # (``splice_blocks_written`` x block bytes), and NOTHING moves
        # collectively (``collective_bytes_per_step`` == 0, the
        # reshard-free splice) — table bytes, not cache bytes.
        from frl_distributed_ml_scaffold_tpu.models.generation import (
            SLOT_LEAF_OF,
            pool_block_bytes,
        )

        model, pool_cache, slot_cache, blk_ids, jaxpr = (
            build_handoff_program()
        )
        census = collective_census(jaxpr)
        flops = jaxpr_flops(jaxpr)
        comm = sum(r.total_bytes for r in census)
        chips = jax.device_count()
        block_size = next(
            l.shape[2]
            for p, l in jax.tree_util.tree_flatten_with_path(pool_cache)[0]
            if getattr(p[-1], "key", None) in SLOT_LEAF_OF
        )
        table_blocks = model.config.seq_len // block_size
        return {
            "flops_per_step": flops,
            "collective_bytes_per_step": comm,
            "collectives": {
                prim: agg
                for prim, agg in sorted(census_summary(census).items())
            },
            "params_bytes": 0,  # the splice never touches params
            "chips": chips,
            "cache_bytes": _tree_bytes(pool_cache),
            "splice_table_bytes": table_blocks * 4,
            "splice_blocks_written": int(blk_ids.shape[0]),
            "splice_block_bytes": pool_block_bytes(pool_cache),
            "intensity_flops_per_byte": round(flops / max(comm, 1), 3),
            "roofline": _roofline(flops, comm, chips),
        }
    build = (
        build_verify_step_program if verify
        else build_paged_decode_step_program if paged
        else build_decode_step_program
    )
    _, params, cache, tok, jaxpr = build()
    census = collective_census(jaxpr)
    flops = jaxpr_flops(jaxpr)
    comm = sum(r.total_bytes for r in census)
    chips = jax.device_count()
    row = {
        "flops_per_step": flops,
        "collective_bytes_per_step": comm,
        "collectives": {
            prim: agg for prim, agg in sorted(census_summary(census).items())
        },
        "params_bytes": _tree_bytes(params),
        "chips": chips,
        "cache_bytes": _tree_bytes(cache),
        "intensity_flops_per_byte": round(flops / max(comm, 1), 3),
        "roofline": _roofline(flops, comm, chips),
    }
    if verify:
        positions = int(tok.shape[1])  # the k+1 tile
        row["positions_per_invocation"] = positions
        row["flops_per_position"] = flops // positions
    return row


def analytic_redistribute_rows() -> dict:
    """Migration rows for the redistribution service (ISSUE 15). The
    executor program rows share graft-lint's ``build_reshard_program``
    artifacts (census bytes = wire cost; ``bytes_moved`` pinned equal to
    the shard-delta ``bytes_lower_bound`` — the 2112.01075 minimality
    claim as a gated number); the ``train_to_serve`` row compiles the
    tiny-GPT fsdp×model → serving-TP tree plan abstractly (nothing
    runs)."""
    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        census_summary,
        collective_census,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        RESHARD_PROGRAMS,
        build_reshard_program,
    )
    from frl_distributed_ml_scaffold_tpu.utils.flops import jaxpr_flops

    rows: dict[str, dict] = {}
    sched = {"declared": "redistribute", "short": "reshard"}
    for name in sorted(RESHARD_PROGRAMS):
        plan, jaxpr, _lowered = build_reshard_program(name)
        census = collective_census(jaxpr)
        comm = sum(r.total_bytes for r in census)
        flops = jaxpr_flops(jaxpr)
        chips = plan.dst_sharding.mesh.size
        rows[REDISTRIBUTE_PREFIX + name.split(":", 1)[1]] = {
            "flops_per_step": flops,
            "collective_bytes_per_step": comm,
            "collectives": {
                prim: agg
                for prim, agg in sorted(census_summary(census).items())
            },
            "params_bytes": plan.leaf_bytes,
            "chips": chips,
            "schedule": sched,
            "bytes_moved": plan.bytes_moved,
            "bytes_lower_bound": plan.bytes_lower_bound,
            "peak_scratch_bytes": plan.peak_scratch_bytes,
            "intensity_flops_per_byte": round(flops / max(comm, 1), 3),
            "roofline": _roofline(flops, comm, chips),
        }

    # The train→serve handoff, tree-level: the shared tiny-GPT abstract
    # twin (analysis.runner.build_train_to_serve_plan — the same plan
    # tools/reshard_plan.py prices, so row and dry-run cannot drift).
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        build_train_to_serve_plan,
    )

    plan, train_env, _serve_env = build_train_to_serve_plan()
    rows[REDISTRIBUTE_PREFIX + "train_to_serve"] = {
        "flops_per_step": 0,
        "collective_bytes_per_step": plan.bytes_moved,
        "collectives": {},
        "params_bytes": plan.total_bytes,
        "chips": train_env.mesh.size,
        "schedule": {"declared": "redistribute", "short": "t2s"},
        "bytes_moved": plan.bytes_moved,
        "bytes_lower_bound": plan.bytes_lower_bound,
        "peak_scratch_bytes": plan.peak_scratch_bytes,
        "plan_kinds": sorted(
            {leaf.kind for leaf in plan.leaves}
        ),
        "intensity_flops_per_byte": 0.0,
        "roofline": _roofline(0, plan.bytes_moved, train_env.mesh.size),
    }
    return rows


def analytic_stage_rows(workdir: str = "/tmp/perf_ledger") -> dict:
    """Per-stage rows for the MPMD pipeline recipe (ISSUE 14): stage j's
    row carries the jaxpr FLOPs + collective census of its microbatch
    fwd+bwd program (within-stage collectives only — the graft-lint
    ``pipeline:stage_program`` family errors on any ``pipe``-axis
    collective), the analytic 1F1B schedule model (bubble fraction,
    whole-schedule and per-stage peak live activations — pinned against
    the driver's measured counters in tests/test_mpmd_pipeline.py), and
    the explicit activation-transfer bytes per microbatch boundary."""
    import jax

    from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
        census_summary,
        collective_census,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        _build_trainer,
    )
    from frl_distributed_ml_scaffold_tpu.parallel.mpmd_pipeline import (
        bubble_fraction,
        peak_live_activations,
        stage_peak_live,
    )
    from frl_distributed_ml_scaffold_tpu.utils.flops import jaxpr_flops

    trainer = _build_trainer(MPMD_RECIPE, workdir)
    runner = trainer._mpmd
    s, mt = runner.num_stages, runner.total_micro
    rows = {}
    for art in runner.lint_artifacts():
        j = art["stage"]
        census = collective_census(art["fwd_bwd_jaxpr"])
        flops = jaxpr_flops(art["fwd_bwd_jaxpr"])
        comm = sum(r.total_bytes for r in census)
        rows[f"{MPMD_STAGE_PREFIX}{j}"] = {
            "flops_per_step": flops,
            "collective_bytes_per_step": comm,
            "collectives": {
                prim: agg
                for prim, agg in sorted(census_summary(census).items())
            },
            "params_bytes": _tree_bytes(art["params_shapes"]),
            "chips": art["chips"],
            "schedule": {
                "declared": f"pipeline(mpmd,1f1b,stages={s},micro={mt})",
                "short": "1f1b",
            },
            "bubble_fraction": bubble_fraction("1f1b", s, mt),
            "peak_live_activations": peak_live_activations("1f1b", s, mt),
            "stage_peak_live": stage_peak_live(j, s, mt),
            "boundary_bytes_per_microbatch": art[
                "boundary_bytes_per_microbatch"
            ],
            "intensity_flops_per_byte": round(flops / max(comm, 1), 3),
            "roofline": _roofline(flops, comm, art["chips"]),
        }
    return rows


def measure_recipe(name: str, steps: int, workdir: str) -> dict:
    """The measured half: a tiny real fit on the CPU sim, reading the
    step-time percentiles the telemetry layer already computes. Wall
    time, not a pin — compared only under --measure-steps, with --tol."""
    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        RECIPE_OVERRIDES,
        _COMMON,
    )
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    cfg = apply_overrides(
        get_config(name),
        _COMMON + RECIPE_OVERRIDES[name] + [
            f"workdir={workdir}",
            f"trainer.total_steps={steps}",
            "trainer.log_every=2",
        ],
    )
    trainer = Trainer(cfg, mesh_env=build_mesh(cfg.mesh))
    _, last = trainer.fit()
    return {
        "steps": steps,
        "step_time_p50_s": float(last.get("step_time_p50_s", 0.0)),
        "step_time_p99_s": float(last.get("step_time_p99_s", 0.0)),
        "samples_per_sec_per_chip": float(
            last.get("samples_per_sec_per_chip", 0.0)
        ),
    }


def measure_serving(n_requests: int = 4) -> dict:
    """TTFT/TPOT percentiles from a tiny warm serve run (the serve_bench
    warm-up discipline: compile-polluted pass dropped via reset_cache)."""
    import jax
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.analysis.runner import (
        build_decode_step_program,
    )
    from frl_distributed_ml_scaffold_tpu.serving.engine import ServingEngine

    model, _, _, _, _ = build_decode_step_program()
    tokens = jax.random.randint(jax.random.key(0), (2, 8), 0, 64)
    params = jax.jit(
        lambda: model.init(
            {"params": jax.random.key(0)}, tokens, train=False
        )["params"]
    )()
    rng = np.random.default_rng(0)
    work = [
        (rng.integers(0, 64, size=int(rng.integers(2, 10))).astype(np.int32),
         int(rng.integers(2, 8)))
        for _ in range(n_requests)
    ]
    eng = ServingEngine(model, params, num_slots=2, temperature=0.0)
    try:
        for prompt, n_new in work:  # warm pass: compiles
            eng.submit(prompt, n_new)
        eng.run()
        eng.reset_cache()
        for prompt, n_new in work:  # measured pass
            eng.submit(prompt, n_new)
        eng.run()
        snap = eng.telemetry.snapshot()
        return {
            "requests": n_requests,
            "ttft_p50_s": snap["serve_ttft_seconds"]["p50"],
            "ttft_p99_s": snap["serve_ttft_seconds"]["p99"],
            "tpot_p50_s": snap["serve_tpot_seconds"]["p50"],
            "tpot_p99_s": snap["serve_tpot_seconds"]["p99"],
        }
    finally:
        eng.close()


def _attribution(row: dict) -> dict:
    """Measured-vs-analytic join: achieved FLOP/s, MFU, and the headroom
    multiple over the roofline lower bound."""
    from frl_distributed_ml_scaffold_tpu.utils.flops import (
        peak_flops_per_chip,
    )

    measured = row.get("measured") or {}
    t = measured.get("step_time_p50_s", 0.0)
    if not t:
        return {}
    flops = row["flops_per_step"]
    chips = row["chips"]
    achieved = flops / t
    lb = max(
        row["roofline"]["compute_s_lower_bound"],
        row["roofline"]["comm_s_lower_bound"],
        1e-12,
    )
    return {
        "achieved_flops_per_s": achieved,
        "mfu": achieved / (chips * peak_flops_per_chip()),
        "headroom_vs_roofline": round(t / lb, 3),
    }


def build_ledger(
    recipes,
    *,
    serving: bool = True,
    measure_steps: int = 0,
    workdir: str = "/tmp/perf_ledger",
) -> dict:
    rows: dict[str, dict] = {}
    for name in recipes:
        print(f"perf_ledger: tracing recipe:{name}", flush=True)
        row = analytic_recipe_row(name, workdir)
        if measure_steps > 0:
            print(f"perf_ledger: measuring recipe:{name} "
                  f"({measure_steps} steps)", flush=True)
            row["measured"] = measure_recipe(name, measure_steps, workdir)
            row["attribution"] = _attribution(row)
        rows[f"recipe:{name}"] = row
    if serving:
        print(f"perf_ledger: tracing {SERVING_PROGRAM}", flush=True)
        row = analytic_serving_row()
        if measure_steps > 0:
            print(f"perf_ledger: measuring {SERVING_PROGRAM}", flush=True)
            row["measured"] = measure_serving()
        rows[SERVING_PROGRAM] = row
        # The paged (block-table) decode step (ISSUE 10): analytic-only —
        # its census/FLOPs gate like every other row; the measured paged
        # serving numbers live in tools/serve_bench.py's paged arms.
        print(f"perf_ledger: tracing {PAGED_SERVING_PROGRAM}", flush=True)
        rows[PAGED_SERVING_PROGRAM] = analytic_serving_row(paged=True)
        # The speculative verify step (ISSUE 11): analytic-only — the
        # k+1-position tile amortizes the pool read, so its
        # flops_per_position row is the analytic twin of serve_bench's
        # measured accepted-per-verify / invocations-per-token columns.
        print(f"perf_ledger: tracing {VERIFY_SERVING_PROGRAM}", flush=True)
        rows[VERIFY_SERVING_PROGRAM] = analytic_serving_row(verify=True)
        # The prefill→decode handoff splice (ISSUE 12): analytic-only —
        # the row pins the splice at table bytes, not cache bytes
        # (ownership = one int32 table row; zero collective bytes), the
        # analytic face of serve_bench's *_disagg tail-isolation columns.
        print(f"perf_ledger: tracing {HANDOFF_PROGRAM}", flush=True)
        rows[HANDOFF_PROGRAM] = analytic_serving_row(handoff=True)
    # MPMD pipeline per-stage rows (ISSUE 14): analytic-only — the
    # measured A/B vs the SPMD backend rides perf_sweep
    # gpt2_pipeline_mpmd (BACKLOG R17-1).
    print(f"perf_ledger: tracing {MPMD_STAGE_PREFIX}* "
          f"({MPMD_RECIPE})", flush=True)
    rows.update(analytic_stage_rows(workdir))
    # Redistribution-service migration rows (ISSUE 15): analytic-only —
    # the measured train→serve arm is queued as BACKLOG R18-1.
    print(f"perf_ledger: tracing {REDISTRIBUTE_PREFIX}*", flush=True)
    rows.update(analytic_redistribute_rows())
    from frl_distributed_ml_scaffold_tpu.utils.flops import (
        peak_flops_per_chip,
    )

    return {
        "version": 1,
        "generated_by": "tools/perf_ledger.py",
        "peak_flops_per_chip": peak_flops_per_chip(),
        "peak_ici_bytes_per_chip_s": peak_ici_bytes_per_chip_s(),
        "rows": rows,
    }


def check_ledger(
    baseline: dict,
    *,
    measure_steps: int = 0,
    tol: float = 3.0,
    workdir: str = "/tmp/perf_ledger",
) -> list[str]:
    """Drift findings (empty = green). Analytic fields compare exactly;
    measured step time within a factor of ``tol`` when re-measured."""
    problems: list[str] = []
    stage_rows: dict | None = None  # rebuilt once on first pipeline: row
    redist_rows: dict | None = None  # rebuilt once on first redistribute:
    for program, base in sorted(baseline.get("rows", {}).items()):
        if program.startswith(REDISTRIBUTE_PREFIX):
            if redist_rows is None:
                try:
                    redist_rows = analytic_redistribute_rows()
                except Exception as e:
                    problems.append(
                        f"{program}: redistribute rows no longer compile "
                        f"({type(e).__name__}: {e})"
                    )
                    redist_rows = {}
            cur = redist_rows.get(program)
            if cur is None:
                if redist_rows:
                    problems.append(
                        f"{program}: baseline redistribute row no longer "
                        f"produced (have: {sorted(redist_rows)})"
                    )
                continue
        elif program.startswith(MPMD_STAGE_PREFIX):
            if stage_rows is None:
                try:
                    stage_rows = analytic_stage_rows(workdir)
                except Exception as e:
                    problems.append(
                        f"{program}: stage rows no longer trace "
                        f"({type(e).__name__}: {e})"
                    )
                    stage_rows = {}
            cur = stage_rows.get(program)
            if cur is None:
                if stage_rows:
                    problems.append(
                        f"{program}: baseline stage row no longer produced "
                        f"(stages: {sorted(stage_rows)})"
                    )
                continue
        elif program in (
            SERVING_PROGRAM, PAGED_SERVING_PROGRAM, VERIFY_SERVING_PROGRAM,
            HANDOFF_PROGRAM,
        ):
            try:
                cur = analytic_serving_row(
                    paged=program == PAGED_SERVING_PROGRAM,
                    verify=program == VERIFY_SERVING_PROGRAM,
                    handoff=program == HANDOFF_PROGRAM,
                )
            except Exception as e:
                problems.append(
                    f"{program}: baseline program no longer traces "
                    f"({type(e).__name__}: {e})"
                )
                continue
        elif program.startswith("recipe:"):
            name = program.split(":", 1)[1]
            try:
                cur = analytic_recipe_row(name, workdir)
            except Exception as e:
                problems.append(
                    f"{program}: baseline recipe no longer traces "
                    f"({type(e).__name__}: {e})"
                )
                continue
        else:
            problems.append(f"{program}: unknown program class in baseline")
            continue
        for key in ANALYTIC_KEYS:
            if base.get(key) != cur.get(key):
                problems.append(
                    f"{program}: {key} drifted — baseline "
                    f"{json.dumps(base.get(key))} vs current "
                    f"{json.dumps(cur.get(key))}"
                )
        for extra in ("cache_bytes", "splice_table_bytes",
                      "splice_blocks_written", "splice_block_bytes",
                      "bubble_fraction", "peak_live_activations",
                      "stage_peak_live", "boundary_bytes_per_microbatch",
                      "bytes_moved", "bytes_lower_bound",
                      "peak_scratch_bytes", "plan_kinds"):
            if extra in base and base[extra] != cur.get(extra):
                problems.append(
                    f"{program}: {extra} drifted — baseline "
                    f"{base[extra]} vs current {cur.get(extra)}"
                )
        if measure_steps > 0 and program.startswith("recipe:"):
            base_t = (base.get("measured") or {}).get("step_time_p50_s", 0.0)
            if base_t > 0:
                name = program.split(":", 1)[1]
                now_t = measure_recipe(name, measure_steps, workdir)[
                    "step_time_p50_s"
                ]
                if now_t > base_t * tol or now_t < base_t / tol:
                    problems.append(
                        f"{program}: measured step_time_p50_s {now_t:.6f}s "
                        f"outside [{base_t / tol:.6f}, {base_t * tol:.6f}] "
                        f"({tol}x band around baseline {base_t:.6f}s)"
                    )
    return problems


def render(ledger: dict, out=sys.stdout) -> None:
    rows = ledger.get("rows", {})
    if not rows:
        return
    width = max(len(p) for p in rows)
    swidth = max(
        [len("schedule")]
        + [
            len((r.get("schedule") or {}).get("short", "-"))
            for r in rows.values()
        ]
    )
    print(
        f"  {'program':<{width}s} {'schedule':<{swidth}s} "
        f"{'flops/step':>12s} {'comm B/step':>12s} "
        f"{'F/B':>10s} {'bound':>8s} {'p50 step s':>11s} {'mfu':>9s}",
        file=out,
    )
    for program, r in sorted(rows.items()):
        measured = r.get("measured") or {}
        t = measured.get("step_time_p50_s", measured.get("tpot_p50_s", 0.0))
        mfu = (r.get("attribution") or {}).get("mfu", 0.0)
        sched = (r.get("schedule") or {}).get("short", "-")
        print(
            f"  {program:<{width}s} {sched:<{swidth}s} "
            f"{r['flops_per_step']:>12.3e} "
            f"{r['collective_bytes_per_step']:>12d} "
            f"{r['intensity_flops_per_byte']:>10.1f} "
            f"{r['roofline']['bound']:>8s} "
            f"{t:>11.6f} {mfu:>9.2e}",
            file=out,
        )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--write", metavar="PATH", default=None,
        help="build the ledger and write it here (the baseline refresh)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="recompute the analytic side and gate against --baseline",
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help=f"baseline path (default: {DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--recipes", default=",".join(DEFAULT_RECIPES),
        help="comma-separated recipe names for --write",
    )
    ap.add_argument(
        "--no-serving", action="store_true",
        help="skip the serving decode-step row",
    )
    ap.add_argument(
        "--measure-steps", type=int, default=0, metavar="N",
        help="also run N-step CPU-sim fits for the measured columns "
        "(and, under --check, re-compare step time within --tol)",
    )
    ap.add_argument(
        "--tol", type=float, default=3.0,
        help="relative band for re-measured step time under --check "
        "(default 3.0 = within 3x either way)",
    )
    ap.add_argument(
        "--workdir", default="/tmp/perf_ledger",
        help="scratch workdir for recipe construction",
    )
    args = ap.parse_args(argv)
    if not args.write and not args.check:
        ap.error("pass --write PATH or --check")

    if args.write:
        ledger = build_ledger(
            [r for r in args.recipes.split(",") if r],
            serving=not args.no_serving,
            measure_steps=args.measure_steps,
            workdir=args.workdir,
        )
        with open(args.write, "w") as fh:
            json.dump(ledger, fh, indent=1, sort_keys=True)
            fh.write("\n")
        render(ledger)
        print(f"wrote {len(ledger['rows'])} rows to {args.write}")
        return 0

    with open(args.baseline) as fh:
        baseline = json.load(fh)
    problems = check_ledger(
        baseline,
        measure_steps=args.measure_steps,
        tol=args.tol,
        workdir=args.workdir,
    )
    render(baseline)
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        print(
            f"perf_ledger: {len(problems)} drift finding(s) vs "
            f"{args.baseline} — if the change is intended, refresh the "
            "baseline in the same commit (--write)"
        )
        return 1
    print(
        f"perf_ledger: {len(baseline.get('rows', {}))} rows match "
        f"{args.baseline}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
