#!/usr/bin/env python
"""Import Hugging Face GPT-2 weights into this framework's GPT params.

Interop path for users migrating from the torch ecosystem: any HF GPT-2
checkpoint (`GPT2LMHeadModel` / `GPT2Model`, any size) converts into the
exact pytree `models/gpt.py` trains — weight-tied head, scanned blocks with
a leading layer dim — ready for fine-tuning or `models/generation.py`
decoding. The reverse direction (`params_to_hf_gpt2`) loads trained params
back into an HF model for publishing (round-trip is byte-exact, tested).
Architecture notes that make the mapping exact:

- HF's Conv1D stores weights as ``[in_features, out_features]`` — already
  flax Dense ``kernel`` layout, no transpose.
- HF fuses q/k/v into ``c_attn`` ``[D, 3D]``; split on the last axis.
- Both use tanh-approximate GeLU, tie ``lm_head`` to ``wte``, and (since
  GPTConfig.layer_norm_epsilon mirrors HF's) share LayerNorm numerics —
  converted logits match HF's forward to float-summation-order tolerance
  (tests/test_hf_import.py).

Usage (offline — point at a local checkpoint directory):

    python tools/import_hf_gpt2.py --hf-dir /path/to/gpt2-medium \
        --out /tmp/gpt2_medium_params.msgpack
    python launch.py --config=gpt2_medium_zero1 ...   # then restore, or
    # load in code: params = load_params("/tmp/gpt2_medium_params.msgpack")
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def hf_gpt2_to_params(hf_model) -> dict:
    """Convert an HF GPT2 (LMHead)Model to the frl GPT params pytree."""
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    n_layer = 1 + max(
        int(k.split(".")[1 if not pre else 2])
        for k in sd
        if k.startswith(f"{pre}h.")
    )

    def stack(fmt: str) -> np.ndarray:
        return np.stack([sd[f"{pre}{fmt.format(i)}"] for i in range(n_layer)])

    c_attn_w = stack("h.{}.attn.c_attn.weight")  # [L, D, 3D], Dense layout
    c_attn_b = stack("h.{}.attn.c_attn.bias")  # [L, 3D]
    q_w, k_w, v_w = np.split(c_attn_w, 3, axis=2)
    q_b, k_b, v_b = np.split(c_attn_b, 3, axis=1)

    def dense(w, b):
        return {"kernel": w, "bias": b}

    def ln(fmt: str):
        return {"scale": stack(fmt + ".weight"), "bias": stack(fmt + ".bias")}

    return {
        "wte": {"embedding": sd[f"{pre}wte.weight"]},
        "wpe": sd[f"{pre}wpe.weight"],
        "blocks": {
            "ln1": ln("h.{}.ln_1"),
            "attn": {
                "query": dense(q_w, q_b),
                "key": dense(k_w, k_b),
                "value": dense(v_w, v_b),
                "out": dense(
                    stack("h.{}.attn.c_proj.weight"),
                    stack("h.{}.attn.c_proj.bias"),
                ),
            },
            "ln2": ln("h.{}.ln_2"),
            "mlp": {
                "fc_in": dense(
                    stack("h.{}.mlp.c_fc.weight"), stack("h.{}.mlp.c_fc.bias")
                ),
                "fc_out": dense(
                    stack("h.{}.mlp.c_proj.weight"),
                    stack("h.{}.mlp.c_proj.bias"),
                ),
            },
        },
        "ln_f": {
            "scale": sd[f"{pre}ln_f.weight"],
            "bias": sd[f"{pre}ln_f.bias"],
        },
    }


def params_to_hf_gpt2(params: dict, hf_model):
    """Inverse of hf_gpt2_to_params: load this framework's GPT params into
    an HF GPT2 (LMHead)Model IN PLACE (fine-tune here, publish there).
    The target model supplies the config; shapes must match."""
    import torch

    sd = hf_model.state_dict()
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    blocks = params["blocks"]
    n_layer = int(np.asarray(blocks["ln1"]["scale"]).shape[0])
    if n_layer != hf_model.config.n_layer:
        raise ValueError(
            f"params carry {n_layer} layers but the target HF model is "
            f"configured for {hf_model.config.n_layer}; a partial load "
            "would silently leave the extra layers randomly initialized"
        )

    def put(key: str, value) -> None:
        # float32 intermediary: torch.from_numpy cannot read ml_dtypes
        # bfloat16 arrays (bf16-trained params); load_state_dict casts to
        # the target parameter dtype on copy.
        arr = torch.from_numpy(
            np.ascontiguousarray(np.asarray(value).astype(np.float32))
        )
        if sd[key].shape != arr.shape:
            raise ValueError(
                f"shape mismatch for {key}: HF {tuple(sd[key].shape)} vs "
                f"converted {tuple(arr.shape)}"
            )
        sd[key] = arr

    put(f"{pre}wte.weight", params["wte"]["embedding"])
    put(f"{pre}wpe.weight", params["wpe"])
    attn, mlp = blocks["attn"], blocks["mlp"]
    for i in range(n_layer):
        put(f"{pre}h.{i}.ln_1.weight", blocks["ln1"]["scale"][i])
        put(f"{pre}h.{i}.ln_1.bias", blocks["ln1"]["bias"][i])
        put(
            f"{pre}h.{i}.attn.c_attn.weight",
            np.concatenate(
                [np.asarray(attn[k]["kernel"][i]) for k in ("query", "key", "value")],
                axis=1,
            ),
        )
        put(
            f"{pre}h.{i}.attn.c_attn.bias",
            np.concatenate(
                [np.asarray(attn[k]["bias"][i]) for k in ("query", "key", "value")]
            ),
        )
        put(f"{pre}h.{i}.attn.c_proj.weight", attn["out"]["kernel"][i])
        put(f"{pre}h.{i}.attn.c_proj.bias", attn["out"]["bias"][i])
        put(f"{pre}h.{i}.ln_2.weight", blocks["ln2"]["scale"][i])
        put(f"{pre}h.{i}.ln_2.bias", blocks["ln2"]["bias"][i])
        put(f"{pre}h.{i}.mlp.c_fc.weight", mlp["fc_in"]["kernel"][i])
        put(f"{pre}h.{i}.mlp.c_fc.bias", mlp["fc_in"]["bias"][i])
        put(f"{pre}h.{i}.mlp.c_proj.weight", mlp["fc_out"]["kernel"][i])
        put(f"{pre}h.{i}.mlp.c_proj.bias", mlp["fc_out"]["bias"][i])
    put(f"{pre}ln_f.weight", params["ln_f"]["scale"])
    put(f"{pre}ln_f.bias", params["ln_f"]["bias"])
    if f"{pre}wte.weight" in sd and "lm_head.weight" in sd:
        sd["lm_head.weight"] = sd[f"{pre}wte.weight"]  # weight tying
    hf_model.load_state_dict(sd)
    return hf_model


def gpt_config_from_hf(hf_config):
    """The matching GPTConfig for a converted checkpoint."""
    from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig

    act = getattr(hf_config, "activation_function", "gelu_new")
    unsupported = {
        "activation_function != gelu_new": act != "gelu_new",
        "scale_attn_by_inverse_layer_idx": bool(
            getattr(hf_config, "scale_attn_by_inverse_layer_idx", False)
        ),
        "reorder_and_upcast_attn": bool(
            getattr(hf_config, "reorder_and_upcast_attn", False)
        ),
    }
    bad = [k for k, v in unsupported.items() if v]
    if bad:
        raise ValueError(
            f"HF config uses variants this GPT cannot reproduce: {bad}; "
            "converting would produce silently wrong logits"
        )
    n_inner = getattr(hf_config, "n_inner", None)
    if n_inner is not None and n_inner != 4 * hf_config.n_embd:
        # GPTConfig expresses the MLP width as an integer ratio.
        if n_inner % hf_config.n_embd:
            raise ValueError(
                f"HF n_inner={n_inner} is not an integer multiple of "
                f"n_embd={hf_config.n_embd}; GPTConfig.mlp_ratio cannot "
                "express this checkpoint"
            )
    ratio = (n_inner // hf_config.n_embd) if n_inner else 4
    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        hidden_dim=hf_config.n_embd,
        seq_len=hf_config.n_positions,
        mlp_ratio=ratio,
        dropout=0.0,
        layer_norm_epsilon=float(
            getattr(hf_config, "layer_norm_epsilon", 1e-5)
        ),
    )


def save_params(params: dict, path: str) -> None:
    from flax import serialization

    with open(path, "wb") as fh:
        fh.write(serialization.to_bytes(params))


def load_params(path: str) -> dict:
    """Inverse of save_params: byte-exact params pytree (numpy leaves)."""
    from flax import serialization

    with open(path, "rb") as fh:
        return serialization.msgpack_restore(fh.read())


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hf-dir", required=True,
                    help="local HF checkpoint directory (no network fetch); "
                         "in --export mode it supplies the target config")
    ap.add_argument("--out", required=True,
                    help="output path: .msgpack (import) or an HF "
                         "save_pretrained directory (--export)")
    ap.add_argument("--export", default=None, metavar="PARAMS_MSGPACK",
                    help="reverse direction: load this framework's params "
                         "file and write an HF checkpoint to --out")
    args = ap.parse_args()

    from transformers import GPT2Config, GPT2LMHeadModel

    if args.export:
        # Config-only target: every tensor gets overwritten, so don't
        # deserialize the (possibly multi-GB) source weights; a bare
        # config directory works too.
        hf_cfg = GPT2Config.from_pretrained(args.hf_dir)
        gpt_config_from_hf(hf_cfg)  # refuses unsupported variants loudly
        hf = GPT2LMHeadModel(hf_cfg)
        params = load_params(args.export)
        params_to_hf_gpt2(params, hf)
        hf.save_pretrained(args.out)
        print(f"wrote HF checkpoint to {args.out} "
              f"(config from {args.hf_dir})")
        return 0

    hf = GPT2LMHeadModel.from_pretrained(args.hf_dir)
    params = hf_gpt2_to_params(hf)
    cfg = gpt_config_from_hf(hf.config)
    save_params(params, args.out)
    n = sum(int(np.prod(x.shape)) for x in
            __import__("jax").tree.leaves(params))
    print(f"wrote {args.out}: {n/1e6:.1f}M params, config {cfg}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
