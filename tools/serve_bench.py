#!/usr/bin/env python
"""Serving throughput/latency A/B: dense-decode vs flash-decode, replicated
vs model-sharded KV cache — and bf16/fp32 vs int8-quantized KV cache —
through the continuous-batching engine.

Runs end-to-end on CPU simulation (the sim devices come from
``--sim-devices``, set BEFORE jax initializes) so the whole pipeline —
bucketed prefill, slot grafts, decode steps, eos retirement — is exercised
without hardware; the on-chip capture at the real operating point is the
queued A/B (BACKLOG R8-1). Measures tokens/sec and p50/p99 per-token
latency per arm — plus the CAPACITY columns the quantized cache is for:
``hbm_bytes_per_slot`` (actual engine cache, scale tensors included —
``generation.cache_bytes_per_slot``), a bf16-cache reference at the same
bucket, and ``max_slots_at_hbm`` under ``--hbm-gb`` of cache budget — and
emits one BENCH_TABLE-schema row per arm (printed as a JSON line;
``--out`` appends to a file). CPU-sim rows are diagnostics — only on-chip
rows get committed to BENCH_TABLE.jsonl.

Arms are
``{dense|flash}_{replicated|sharded}[_paged][_int8|_fp8][_spec[_ngram|_draft]]``;
the ``_int8`` suffix serves the same workload with
``model.kv_cache_quant=int8`` (``_fp8`` maps to ``fp8_e4m3``), and the
``_paged`` suffix (ISSUE 10) serves it through the block-table pool
engine (``--block-size``/``--pool-blocks``). Paged arms report the paged
capacity columns — block bytes, measured peak pool blocks, HBM per
ACTIVE slot (peak blocks x block bytes / slots, prefix sharing counted
once) and the resulting ``max_slots_at_hbm`` — and additionally run a
SHARED-PREFIX workload (a few unique system prompts, several requests
each) whose ``serving.prefix`` sub-dict shows prefill work scaling with
unique prefixes rather than requests, measured per request via
``Completion.prefix_cache_hit`` / ``prefill_tokens_saved``.

The ``_spec`` suffix (ISSUE 11, paged arms only) serves the workload
with speculative decoding — ``_spec_ngram`` (default) drafts via
prompt-lookup self-speculation, ``_spec_draft`` via a tiny draft GPT
sharing the tokenizer (``--speculate-k`` drafts per verify). Spec arms
report acceptance-rate, mean-accepted-per-verify, and
decode-invocations-per-token next to the TTFT/TPOT columns, and
additionally run a REPETITIVE-TEXT workload (periodic prompts whose
greedy continuations cycle — where n-gram drafting shines) whose
``serving.spec_repetitive`` sub-dict measures the speculative headline:
mean accepted tokens per verify and the invocations-per-token reduction
vs a ``speculate=off`` engine on the same workload. Output is
token-identical either way (greedy acceptance is exact), so the columns
are pure perf.

The ``_disagg`` suffix (ISSUE 12, paged arms only) serves the workload
through the disaggregated prefill/decode scheduler
(serving/scheduler.py) and additionally runs a MIXED BURST workload —
a decode-heavy latency tenant under a prefill-heavy best-effort burst —
through BOTH engines, reporting decode TPOT p99 under the burst for
each (``serving.disagg``): colocated admission prefills into every free
slot inline before each decode tick, so the burst lands in the decode
tenant's inter-token gaps; the scheduler's decoupled admission defers
the burst instead (tail isolation, pinned >= 2x in test_serving.py).
The handoff is a block-table splice — ``handoff_transfer_bytes`` is 0
when the partitions share the pool (re-own). Under ``--chaos`` the
disagg sub-dict adds a worker-fault pass (``serve.prefill_worker`` /
``serve.handoff`` injections re-queue; every request still resolves).

    python tools/serve_bench.py --preset tiny --requests 12 --slots 4
    python tools/serve_bench.py --preset tiny --arms flash_sharded,flash_sharded_int8
    python tools/serve_bench.py --preset tiny --arms flash_replicated,flash_replicated_paged
    python tools/serve_bench.py --preset tiny --arms flash_replicated_paged_spec_ngram
    python tools/serve_bench.py --preset tiny --arms flash_replicated_paged_disagg
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "gpt2_medium"],
                   help="model size (tiny = CPU-sim friendly)")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--max-new", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sim-devices", type=int, default=8,
                   help="CPU-sim device count (0 = leave backend alone)")
    p.add_argument("--arms", default="dense_replicated,flash_replicated,"
                   "dense_sharded,flash_sharded,flash_replicated_int8,"
                   "flash_sharded_int8,flash_replicated_paged,"
                   "flash_replicated_paged_int8,"
                   "flash_replicated_paged_spec_ngram,"
                   "flash_replicated_paged_disagg",
                   help="comma-separated: {dense,flash}_{replicated,"
                   "sharded}[_paged][_int8|_fp8][_spec[_ngram|_draft]]"
                   "[_disagg]")
    p.add_argument("--model-axis", type=int, default=2,
                   help="model-axis size for the sharded arms")
    p.add_argument("--block-size", type=int, default=16,
                   help="KV block size (tokens) for the paged arms "
                   "(power of two)")
    p.add_argument("--pool-blocks", type=int, default=0,
                   help="KV pool size in blocks for the paged arms "
                   "(0 = auto: never blocks admission; the capacity "
                   "column prices slots at MEASURED peak blocks either "
                   "way)")
    p.add_argument("--speculate-k", type=int, default=4,
                   help="draft tokens per verify step for the _spec arms")
    p.add_argument("--hbm-gb", type=float, default=16.0,
                   help="per-replica KV-cache HBM budget for the "
                   "max-concurrent-slots column")
    p.add_argument("--out", default=None,
                   help="append emitted rows to this jsonl file")
    p.add_argument("--chaos", action="store_true",
                   help="after the measured pass, serve the workload "
                   "again under injected faults (bounded queue, tiny "
                   "deadlines on every 3rd request, one poison prefill) "
                   "and report shed rate, deadline-miss rate, and "
                   "non-faulted-request p99 in serving.chaos")
    return p.parse_args(argv)


def _setup_backend(args) -> None:
    """Must run before jax import (the conftest.py discipline)."""
    if args.sim_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.sim_devices}"
            ).strip()


#: v5e bf16 peak — the MFU convention every BENCH_TABLE row uses; on CPU
#: sim the resulting mfu is a nominal tiny-but-positive placeholder.
_PEAK_FLOPS = 197e12


def _build(preset: str):
    import jax

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    if preset == "tiny":
        # heads=2 (head_dim 32): CPU-sim friendly while keeping the
        # head_dim representative enough that the int8 arms' bytes-per-
        # slot accounting reflects real geometry (scale overhead is
        # 2/head_dim of the payload — at head_dim 8 it would dominate).
        cfg = GPTConfig(
            vocab_size=256, num_layers=2, num_heads=2, hidden_dim=64,
            seq_len=256, dropout=0.0,
        )
    else:
        cfg = GPTConfig(
            vocab_size=50257, num_layers=24, num_heads=16, hidden_dim=1024,
            seq_len=1024, dropout=0.0,
        )
    model = GPT(cfg, get_policy(PrecisionConfig(policy="fp32")))
    tokens = jax.random.randint(
        jax.random.key(0), (2, 8), 0, cfg.vocab_size
    )
    params = jax.jit(
        lambda: model.init(
            {"params": jax.random.key(0)}, tokens, train=False
        )["params"]
    )()
    return model, params


def _workload(cfg, n_requests: int, max_new: int, seed: int):
    import numpy as np

    rng = np.random.default_rng(seed)
    ceil = max(4, min(cfg.seq_len - max_new - 1, cfg.seq_len // 4))
    work = []
    for _ in range(n_requests):
        l = int(rng.integers(2, ceil))
        n_new = int(rng.integers(max(1, max_new // 2), max_new + 1))
        # Clamp to the model context so an aggressive --max-new degrades
        # to shorter generations instead of aborting the A/B at submit().
        work.append(
            (
                rng.integers(0, cfg.vocab_size, size=l).astype(np.int32),
                max(1, min(n_new, cfg.seq_len - l)),
            )
        )
    return work


def _decode_flops_per_token(model, params, num_slots: int) -> int:
    """Jaxpr-counted FLOPs of one decode step / slots (the per-token cost
    at full occupancy — the utils/flops.py counter, same convention as the
    BENCH_TABLE backfills)."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.utils.flops import fn_flops

    m = model.clone(cache_len=model.config.seq_len)
    tok = jnp.zeros((num_slots, 1), jnp.int32)
    _, vars_out = m.apply(
        {"params": params}, tok, decode=True, mutable=["cache"]
    )
    cache = vars_out["cache"]

    def step(params, cache, tok):
        out, vo = m.apply(
            {"params": params, "cache": cache}, tok, decode=True,
            mutable=["cache"],
        )
        return out, vo["cache"]

    return fn_flops(step, params, cache, tok) // num_slots


def _chaos_pass(
    model, run_params, args, work, kv_kwargs=None, draft_kwargs=None
) -> dict:
    """Serve the workload again under injected faults (ISSUE 9): a
    bounded admission queue (2x slots) sheds the submit burst's tail, a
    microscopic deadline on every 3rd request forces typed deadline
    misses, and the second request's prefill is poisoned via the
    ``serve.prefill`` fault site. A speculative engine additionally gets
    its draft proposer failed once via ``serve.draft`` (ISSUE 11) — the
    hit slot degrades to plain decode, counted, output unchanged.
    Reports the degradation headline: shed rate, deadline-miss rate,
    quarantine count, and the p50/p99 token latency of the NON-faulted
    requests — the number that proves chaos does not bleed into healthy
    traffic (tests/test_faults.py pins the stronger token-identity
    form)."""
    import numpy as np

    from frl_distributed_ml_scaffold_tpu import faults
    from frl_distributed_ml_scaffold_tpu.config.schema import ServingConfig
    from frl_distributed_ml_scaffold_tpu.faults import FaultPlan
    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    eng = ServingEngine(
        model, run_params, num_slots=args.slots, temperature=0.0,
        serving=ServingConfig(
            max_queue_depth=max(2, args.slots * 2), **(kv_kwargs or {})
        ),
        **(draft_kwargs or {}),
    )
    # Warm-up discipline (the measured-pass contract everywhere in this
    # tool): compile every shape the chaos pass will hit, then reset, so
    # nonfaulted_p99 measures serving under chaos — not XLA. The warm
    # pass must submit INSIDE the queue bound (no faults armed yet).
    for prompt, n_new in work:
        eng.submit(prompt, n_new)
        eng.run()
    eng.reset_cache()
    # The warm pass consumed ids 0..n-1: the chaos pass's ids continue at
    # n, so the poison key targets its SECOND request (id n+1) — inside
    # the queue bound, failing at prefill.
    specs = [dict(site="serve.prefill", key=str(len(work) + 1), times=0)]
    if (kv_kwargs or {}).get("speculate", "off") != "off":
        # Fail the first draft-proposal consultation: the hit slot
        # degrades to plain single-token decode (sticky for its
        # request) and the run completes token-identically.
        specs.append(dict(site="serve.draft", at=1, times=1))
    plan = FaultPlan(specs, seed=args.seed)
    with faults.active(plan):
        for i, (prompt, n_new) in enumerate(work):
            eng.submit(
                prompt, n_new, deadline_s=1e-4 if i % 3 == 2 else 0.0
            )
        done = eng.run()
    eng.close()
    assert len(done) == len(work), (len(done), len(work))
    n = len(done)
    by_reason: dict[str, int] = {}
    for c in done:
        by_reason[c.finish_reason] = by_reason.get(c.finish_reason, 0) + 1
    ok = [c for c in done if c.ok]
    lat = [dt for c in ok for dt in c.token_latencies_s]
    return {
        "requests": n,
        "max_queue_depth": eng.max_queue_depth,
        "injected": dict(plan.injected),
        "by_reason": by_reason,
        "shed_rate": round(by_reason.get("shed", 0) / n, 4),
        "deadline_miss_rate": round(by_reason.get("deadline", 0) / n, 4),
        "quarantined": by_reason.get("error", 0),
        "completed_ok": len(ok),
        "nonfaulted_p50_ms": (
            round(float(np.percentile(lat, 50)) * 1e3, 3) if lat else 0.0
        ),
        "nonfaulted_p99_ms": (
            round(float(np.percentile(lat, 99)) * 1e3, 3) if lat else 0.0
        ),
        "draft_failures": int(eng.stats["spec_draft_failures"]),
    }


def _bucketed_ref_bucket(cfg, work) -> int:
    """The terminal cache bucket the BUCKETED engine reaches on this
    workload (every slot pays it — the shared slot-array bucket grows to
    the largest active row): the honest bf16 reference the paged
    capacity ratio is measured against."""
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        next_cache_bucket,
    )

    need = max(len(p) + n_new for p, n_new in work)
    return next_cache_bucket(cfg.seq_len, need)


def _prefix_pass(model, run_params, args, kv_kwargs) -> dict:
    """Shared-prefix workload through the paged engine (ISSUE 10
    acceptance): a few unique "system prompts" (each an exact number of
    KV blocks), several requests per prompt with short unique tails.
    Reports prefill work against the no-sharing cost, so the headline —
    prefill scales with UNIQUE prefixes, not requests — is a measured
    column, corroborated per request by the Completion SLO fields."""
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    bs = kv_kwargs["kv_block_size"]
    vocab = model.config.vocab_size
    rng = np.random.default_rng(args.seed + 1)
    uniq, per, prefix_blocks = 3, 3, 2
    work = []
    for _ in range(uniq):
        pre = rng.integers(0, vocab, size=prefix_blocks * bs)
        for _ in range(per):
            tail = rng.integers(0, vocab, size=int(rng.integers(2, 6)))
            work.append(np.concatenate([pre, tail]).astype(np.int32))
    # The prefix pass measures prefix caching, not speculation — strip
    # the spec knobs so spec arms reuse it unchanged.
    kv_kwargs = {
        k: v for k, v in kv_kwargs.items() if not k.startswith("speculate")
    }
    eng = ServingEngine(
        model, run_params, num_slots=args.slots, temperature=0.0,
        **kv_kwargs,
    )
    for p in work:
        eng.submit(p, 4)
    done = eng.run()
    eng.close()
    assert len(done) == len(work), (len(done), len(work))
    prompt_tokens = int(sum(len(p) for p in work))
    prefilled = int(eng.stats["prefill_tokens"])
    saved = int(eng.stats["prefill_tokens_saved"])
    return {
        "unique_prefixes": uniq,
        "requests_per_prefix": per,
        "requests": len(work),
        "prefix_blocks": prefix_blocks,
        "prompt_tokens_total": prompt_tokens,
        "prefill_tokens": prefilled,
        "prefill_tokens_saved": saved,
        "prefix_hits": int(eng.stats["prefix_hits"]),
        "prefix_hit_rate": round(
            eng.stats["prefix_hits"] / len(work), 4
        ),
        # Per-request corroboration (the Completion SLO fields): the
        # aggregate savings must be exactly the sum of what each
        # completion says it saved.
        "per_request_hits": int(sum(c.prefix_cache_hit for c in done)),
        "per_request_tokens_saved": int(
            sum(c.prefill_tokens_saved for c in done)
        ),
    }


def _build_draft(cfg):
    """Tier-B draft model for the _spec_draft arms: a 1-layer GPT
    sharing the target's tokenizer (vocab), ~1/8 the width — small
    enough that a propose round costs a fraction of a verify step."""
    import jax

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    dcfg = GPTConfig(
        vocab_size=cfg.vocab_size, num_layers=1, num_heads=2,
        hidden_dim=max(32, cfg.hidden_dim // 8), seq_len=cfg.seq_len,
        dropout=0.0,
    )
    draft = GPT(dcfg, get_policy(PrecisionConfig(policy="fp32")))
    tokens = jax.random.randint(
        jax.random.key(7), (2, 8), 0, dcfg.vocab_size
    )
    dparams = jax.jit(
        lambda: draft.init(
            {"params": jax.random.key(7)}, tokens, train=False
        )["params"]
    )()
    return dict(draft_model=draft, draft_params=dparams)


def _simulate_ngram_serving(prompt, cont, k: int) -> tuple[int, int]:
    """Replay the engine's tier-A accept loop on a KNOWN greedy
    continuation, host-side: returns (tokens emitted, verify steps).
    Greedy decode is deterministic, so this is exactly what the engine
    will do — the workload builder uses it to SCORE candidate texts by
    repetitiveness (no device work)."""
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.serving.engine import ngram_propose

    hist = np.asarray(prompt)
    i, verifies = 0, 0
    while i < len(cont):
        r = len(cont) - i
        d = ngram_propose(hist, min(k, r - 1)) if r >= 2 else hist[:0]
        a = 0
        while a < d.size and d[a] == cont[i + a]:
            a += 1
        emitted = min(a + 1, r)
        verifies += 1
        hist = np.concatenate([hist, cont[i : i + emitted]])
        i += emitted
    return len(cont), verifies


def _spec_workload(model, params, n_requests: int, max_new: int, seed: int,
                   k: int = 4):
    """REPETITIVE-TEXT workload for the speculative arms: each prompt is
    a short random seed plus a prefix of the model's OWN greedy
    continuation — the prompt-lookup setting (extraction, templated
    completion, code) where the text the model is about to emit repeats
    n-grams already present in its context. Candidate texts are scored
    by simulated drafting acceptance (``_simulate_ngram_serving`` —
    greedy decode is deterministic, so the score is exact) and the most
    REPETITIVE continuations are kept: this sub-workload measures the
    text class n-gram drafting targets, the way the shared-prefix
    workload measures common-system-prompt traffic. Random-weight tiny
    models write noisier text than trained ones, so the selection pool
    is a few times the request count."""
    import jax.numpy as jnp
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.models.generation import generate

    cfg = model.config
    rng = np.random.default_rng(seed + 2)
    carry = min(32, cfg.seq_len // 8)  # continuation tokens in the prompt
    n_new = min(max(max_new, 64), cfg.seq_len // 2)
    scored = []
    for _ in range(3 * n_requests):
        s = rng.integers(0, cfg.vocab_size, size=int(rng.integers(4, 9)))
        full = np.asarray(
            generate(
                model, params, jnp.asarray(s.astype(np.int32))[None],
                max_new_tokens=carry + n_new, temperature=0.0,
            )
        )[0].astype(np.int32)
        prompt = full[: s.size + carry]
        budget = min(n_new, cfg.seq_len - prompt.size)
        cont = full[prompt.size : prompt.size + budget]
        tokens, verifies = _simulate_ngram_serving(prompt, cont, k)
        scored.append((tokens / max(verifies, 1), prompt, budget))
    scored.sort(key=lambda t: -t[0])
    return [(p, b) for _, p, b in scored[:n_requests]]


def _spec_pass(model, run_params, args, kv_kwargs, draft_kwargs) -> dict:
    """The speculative headline, measured (ISSUE 11 acceptance): serve
    the repetitive-text workload through the spec engine AND through a
    speculate=off paged engine, and report mean accepted tokens per
    verify step plus the target-model decode-invocations-per-token
    reduction. Both engines follow the warm-up discipline; outputs are
    token-identical by the greedy-acceptance contract (pinned in
    tests/test_serving.py), so this sub-dict is pure perf."""
    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    work = _spec_workload(
        model, run_params, max(4, args.slots), args.max_new, args.seed,
        k=kv_kwargs.get("speculate_k", 4),
    )

    def serve(spec: bool):
        kw = dict(kv_kwargs)
        dk = dict(draft_kwargs) if spec else {}
        if not spec:
            kw.pop("speculate", None)
            kw.pop("speculate_k", None)
        eng = ServingEngine(
            model, run_params, num_slots=args.slots, temperature=0.0,
            **kw, **dk,
        )
        for prompt, n_new in work:  # warm pass: compiles
            eng.submit(prompt, n_new)
        eng.run()
        eng.reset_cache()
        for prompt, n_new in work:  # measured pass
            eng.submit(prompt, n_new)
        done = eng.run()
        eng.close()
        assert len(done) == len(work), (len(done), len(work))
        return eng, done

    eng, done = serve(spec=True)
    eng_off, _ = serve(spec=False)
    s = eng.stats
    verifies = max(int(s["spec_slot_verifies"]), 1)
    inv = s["slot_steps"] / max(s["step_tokens"], 1)
    inv_off = eng_off.stats["slot_steps"] / max(
        eng_off.stats["step_tokens"], 1
    )
    return {
        "mode": kv_kwargs.get("speculate", "ngram"),
        "k": kv_kwargs.get("speculate_k", 0),
        "requests": len(work),
        "tokens": int(s["step_tokens"]),
        "proposed": int(s["spec_proposed"]),
        "accepted": int(s["spec_accepted"]),
        "acceptance_rate": round(
            s["spec_accepted"] / max(s["spec_proposed"], 1), 4
        ),
        "mean_accepted_per_verify": round(s["spec_emitted"] / verifies, 4),
        "verify_steps": int(s["decode_verify"]),
        "decode_invocations_per_token": round(inv, 4),
        "off_decode_invocations_per_token": round(inv_off, 4),
        "invocations_reduction_x": round(inv_off / max(inv, 1e-9), 4),
        "per_request_accept_rate_mean": round(
            sum(c.spec_accept_rate for c in done) / len(done), 4
        ),
    }


def _disagg_workload(cfg, slots: int, max_new: int, seed: int):
    """The mixed prefill-heavy/decode-heavy workload the disaggregation
    A/B serves: a small DECODE-HEAVY foreground (short prompts, long
    budgets — the latency tenant whose TPOT tail is measured) plus a
    PREFILL-HEAVY burst (near-half-context prompts, budget 1 — the
    embedding/classification shape that is pure prefill, the workload
    disaggregation exists for)."""
    import numpy as np

    rng = np.random.default_rng(seed + 5)
    vocab = cfg.vocab_size
    dec_budget = max(16, min(2 * max_new, cfg.seq_len // 8))
    dec = [
        (rng.integers(0, vocab, size=int(rng.integers(4, 9)))
         .astype(np.int32), dec_budget)
        for _ in range(2)
    ]
    long_l = cfg.seq_len // 2
    pre = [
        (rng.integers(0, vocab, size=long_l - int(rng.integers(0, 8)))
         .astype(np.int32), 1)
        for _ in range(3 * slots)
    ]
    return dec, pre


def _decode_gaps_ms(done, dec_ids):
    """Inter-token gaps (ms) of the decode-heavy requests, from the
    Completion token-arrival times — the TPOT a decoding tenant actually
    experiences, inline prefill stalls included."""
    import numpy as np

    gaps = []
    for c in done:
        if c.id in dec_ids and len(c.token_times_s) > 1:
            gaps.extend(np.diff(np.asarray(c.token_times_s)) * 1e3)
    return np.asarray(gaps, np.float64)


def _disagg_pass(model, run_params, args, kv_kwargs) -> dict:
    """The disaggregation headline, measured (ISSUE 12 acceptance):
    serve the mixed burst workload through the colocated paged engine
    AND through the disaggregated scheduler, and report decode TPOT
    under the prefill burst for both. Colocated admission runs a full
    prefill into EVERY free slot inline before each decode tick, so the
    burst's wall time lands inside the foreground's inter-token gaps;
    the scheduler's decoupled admission (``prefill_max_per_tick``)
    defers the burst instead — tail isolation without touching decode
    throughput. Both passes follow the warm-up discipline; outputs are
    token-identical (pinned in tests/test_serving.py), so the columns
    are pure scheduling. With ``--chaos``, a third disaggregated pass
    injects the ``serve.prefill_worker``/``serve.handoff`` sites and
    proves the re-queue path: every request still resolves."""
    import numpy as np

    from frl_distributed_ml_scaffold_tpu import faults
    from frl_distributed_ml_scaffold_tpu.faults import FaultPlan
    from frl_distributed_ml_scaffold_tpu.serving import (
        DisaggServingEngine,
        ServingEngine,
        TenantSpec,
    )

    slots = max(args.slots, 6)
    dec, pre = _disagg_workload(
        model.config, slots, args.max_new, args.seed
    )
    kv = {
        k: v for k, v in kv_kwargs.items() if not k.startswith("speculate")
    }

    def serve(disagg: bool, plan=None):
        if disagg:
            eng = DisaggServingEngine(
                model, run_params, num_slots=slots, temperature=0.0,
                tenants=[
                    TenantSpec("fg", "latency"),
                    TenantSpec("bg", "best_effort"),
                ],
                **kv,
            )
        else:
            eng = ServingEngine(
                model, run_params, num_slots=slots, temperature=0.0, **kv
            )

        def submit_all():
            ids = []
            for p, n in dec:
                ids.append(
                    eng.submit(p, n, tenant="fg") if disagg
                    else eng.submit(p, n)
                )
            for p, n in pre:
                (eng.submit(p, n, tenant="bg") if disagg
                 else eng.submit(p, n))
            return set(ids)

        submit_all()  # warm pass: compiles every shape
        eng.run()
        eng.reset_cache()
        if plan is not None:
            with faults.active(plan):
                dec_ids = submit_all()
                done = eng.run()
        else:
            dec_ids = submit_all()
            done = eng.run()
        eng.close()
        assert len(done) == len(dec) + len(pre), (len(done),)
        return eng, done, dec_ids

    eng_c, done_c, ids_c = serve(disagg=False)
    eng_d, done_d, ids_d = serve(disagg=True)
    gaps_c = _decode_gaps_ms(done_c, ids_c)
    gaps_d = _decode_gaps_ms(done_d, ids_d)
    colo_p99 = float(np.percentile(gaps_c, 99))
    dis_p99 = float(np.percentile(gaps_d, 99))
    handoff_h = eng_d.telemetry.histogram("serve_handoff_seconds")
    out = {
        "slots": slots,
        "decode_requests": len(dec),
        "burst_requests": len(pre),
        "decode_budget": int(dec[0][1]),
        "burst_prompt_tokens": int(sum(len(p) for p, _ in pre)),
        # The acceptance number: decode TPOT p99 UNDER THE PREFILL
        # BURST, colocated vs disaggregated (gap-based — the tail the
        # decoding tenant actually sees).
        "colocated_decode_tpot_p50_ms": round(
            float(np.percentile(gaps_c, 50)), 3
        ),
        "colocated_decode_tpot_p99_ms": round(colo_p99, 3),
        "disagg_decode_tpot_p50_ms": round(
            float(np.percentile(gaps_d, 50)), 3
        ),
        "disagg_decode_tpot_p99_ms": round(dis_p99, 3),
        "tail_isolation_x": round(colo_p99 / max(dis_p99, 1e-9), 4),
        "handoffs": int(eng_d.stats["handoffs"]),
        "handoff_p50_ms": round(handoff_h.quantile(0.50) * 1e3, 3),
        "prefill_deferred": int(eng_d.stats["prefill_deferred"]),
        "preemptions": int(eng_d.stats["preemptions"]),
        # 0 when the partitions share the pool: the splice is a re-own.
        "handoff_transfer_bytes": int(
            eng_d.stats["handoff_transfer_bytes"]
        ),
    }
    if args.chaos:
        # Worker-boundary chaos (the serve.prefill_worker/serve.handoff
        # sites): one prefill-worker death and one handoff failure mid
        # burst — both re-queue and every request still resolves (the
        # assert inside serve()), the never-hangs contract across the
        # worker boundary.
        plan = FaultPlan(
            [
                dict(site="serve.prefill_worker", at=2, times=1),
                dict(site="serve.handoff", at=3, times=1),
            ],
            seed=args.seed,
        )
        eng_f, done_f, _ = serve(disagg=True, plan=plan)
        out["chaos"] = {
            "injected": dict(plan.injected),
            "prefill_worker_failures": int(
                eng_f.stats["prefill_worker_failures"]
            ),
            "handoff_failures": int(eng_f.stats["handoff_failures"]),
            "requeued": int(
                eng_f.stats["prefill_worker_requeued"]
                + eng_f.stats["handoff_requeued"]
            ),
            "completed": len(done_f),
            "completed_ok": sum(1 for c in done_f if c.ok),
        }
    return out


def run_arm(model, params, arm: str, args, flops_per_token: int) -> dict:
    """One (decode impl, sharding) arm through the engine; returns the
    BENCH_TABLE-schema row."""
    import dataclasses
    import datetime

    import jax
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.config.schema import MeshConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        build_mesh,
        mesh_context,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT, gpt_tp_rules
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        shard_params_for_serving,
    )
    from frl_distributed_ml_scaffold_tpu.serving import ServingEngine

    parts = arm.split("_")
    suffixes = parts[2:]
    paged = "paged" in suffixes
    quants = [s for s in suffixes if s in ("int8", "fp8")]
    spec = "spec" in suffixes
    spec_mode = "draft" if "draft" in suffixes else "ngram"
    disagg = "disagg" in suffixes
    if (
        len(parts) < 2
        or parts[0] not in ("dense", "flash")
        or parts[1] not in ("replicated", "sharded")
        or len(quants) > 1
        or any(s not in ("paged", "int8", "fp8", "spec", "ngram", "draft",
                         "disagg")
               for s in suffixes)
        or (("ngram" in suffixes or "draft" in suffixes) and not spec)
        or (spec and not paged)
        or (disagg and not paged)
    ):
        raise ValueError(
            f"unknown arm {arm!r}: want {{dense,flash}}_{{replicated,"
            "sharded}[_paged][_int8|_fp8][_spec[_ngram|_draft]][_disagg] "
            "(spec and disagg require paged)"
        )
    impl, sharding = parts[:2]
    quant = {"int8": "int8", "fp8": "fp8_e4m3"}[quants[0]] if quants else "none"
    m = dataclasses.replace(
        model.config, decode_attention=impl, kv_cache_quant=quant
    )
    model = GPT(m, model.policy)

    mesh_sizes = {"pipe": 1, "data": 1, "fsdp": 1, "seq": 1, "expert": 1,
                  "model": 1}
    if sharding == "sharded":
        n = len(jax.devices())
        tp = args.model_axis
        if n % tp != 0 or model.config.num_heads % tp != 0:
            raise ValueError(
                f"sharded arm needs model axis {tp} dividing both device "
                f"count {n} and num_heads {model.config.num_heads}"
            )
        env = build_mesh(MeshConfig(data=n // tp, model=tp))
        mesh_sizes.update(data=n // tp, model=tp)
        with mesh_context(env):
            run_params = shard_params_for_serving(params, env, gpt_tp_rules())
    else:
        env = None
        run_params = params

    work = _workload(model.config, args.requests, args.max_new, args.seed)
    kv_kwargs = (
        dict(kv_block_size=args.block_size, kv_pool_blocks=args.pool_blocks)
        if paged else {}
    )
    draft_kwargs = {}
    if spec:
        kv_kwargs.update(speculate=spec_mode, speculate_k=args.speculate_k)
        if spec_mode == "draft":
            draft_kwargs = _build_draft(model.config)
    with mesh_context(env):
        if disagg:
            # The disaggregated facade serves the main pass (same public
            # API; single default tenant) — the burst A/B sub-dict below
            # additionally compares it against the colocated engine.
            from frl_distributed_ml_scaffold_tpu.serving import (
                DisaggServingEngine,
            )

            eng = DisaggServingEngine(
                model, run_params, num_slots=args.slots, temperature=0.0,
                **kv_kwargs, **draft_kwargs,
            )
        else:
            eng = ServingEngine(
                model, run_params, num_slots=args.slots, temperature=0.0,
                **kv_kwargs, **draft_kwargs,
            )
        # Warm-up pass: the SAME workload once through the engine, so
        # every compiled shape the measured pass will hit (each prompt
        # bucket's prefill, each cache bucket's decode step, the grafts
        # and growths between them) is already in the jit caches — the
        # timed window must measure serving, not XLA compilation, or the
        # A/B reads as whichever arm compiles fewer programs. The cache
        # state is then RESET so the measured pass replays the same
        # bucket trajectory (same shapes, warm) instead of decoding
        # everything at the warm pass's terminal bucket.
        for prompt, n_new in work:
            eng.submit(prompt, n_new)
        eng.run()
        eng.reset_cache()
        for prompt, n_new in work:
            eng.submit(prompt, n_new)
        t0 = time.perf_counter()
        done = eng.run()
        wall = time.perf_counter() - t0
    assert len(done) == len(work), (len(done), len(work))
    chaos = None
    if args.chaos:
        with mesh_context(env):
            chaos = _chaos_pass(
                model, run_params, args, work, kv_kwargs, draft_kwargs
            )

    # Capacity accounting (the quantized-cache arms' raison d'être):
    # actual per-slot bytes of the terminal-bucket engine cache (scale
    # tensors included) vs a bf16-cache reference at the SAME bucket, and
    # the concurrent slots each fits in the --hbm-gb cache budget.
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        estimate_cache_bytes_per_slot,
    )

    bytes_per_slot = eng.bytes_per_slot()
    hbm_budget = int(args.hbm_gb * (1 << 30))
    paged_cols = None
    if paged:
        # Paged capacity accounting: a concurrent slot costs what its
        # requests actually allocated — MEASURED peak pool blocks (prefix
        # sharing counted once, worst-case reservations included) spread
        # over the slot array, priced at actual block bytes. The bf16
        # bucketed reference is what the same workload costs the legacy
        # engine: every slot pays the terminal bucket.
        block_bytes = eng.block_bytes()
        peak_blocks = int(eng.stats["pool_peak_blocks"])
        bytes_per_active_slot = max(
            1, block_bytes * peak_blocks // args.slots
        )
        # Dtype-consistent reference: the paged win is STRUCTURAL (fewer
        # tokens held), so the bucketed reference prices its cache in
        # the same element width the measured pool actually uses (fp32
        # on the CPU sim, bf16 on chip) — except the quantized-pool
        # arms, whose reference stays bf16 (the compounding claim:
        # 1-byte pool vs bf16 buckets).
        import numpy as np

        ref_elem = (
            2 if quant != "none"
            else np.dtype(model.policy.compute_dtype).itemsize
        )
        bytes_bf16_ref = estimate_cache_bytes_per_slot(
            dataclasses.replace(model.config, kv_cache_quant="none"),
            _bucketed_ref_bucket(model.config, work),
            kv_dtype_bytes=ref_elem,
        )
        paged_cols = {
            "block_size": eng.block_size,
            "pool_blocks": eng.pool_blocks,
            "block_bytes": block_bytes,
            "pool_peak_blocks": peak_blocks,
            "pool_peak_utilization": round(
                peak_blocks / max(eng.pool_blocks - 1, 1), 4
            ),
            "hbm_bytes_per_active_slot": bytes_per_active_slot,
            "prefix_hits": int(eng.stats["prefix_hits"]),
            "prefill_tokens": int(eng.stats["prefill_tokens"]),
            "prefill_tokens_saved": int(
                eng.stats["prefill_tokens_saved"]
            ),
            # (prefix_hit_rate lives in the arm-uniform top-level
            # serving columns, not here — one site, no drift.)
        }
        max_slots = hbm_budget // bytes_per_active_slot
    else:
        bytes_bf16_ref = estimate_cache_bytes_per_slot(
            dataclasses.replace(model.config, kv_cache_quant="none"),
            eng.bucket, kv_dtype_bytes=2,
        )
        max_slots = hbm_budget // max(bytes_per_slot, 1)
    prefix = None
    if paged:
        with mesh_context(env):
            prefix = _prefix_pass(model, run_params, args, kv_kwargs)
    specd = None
    if spec:
        with mesh_context(env):
            specd = _spec_pass(
                model, run_params, args, kv_kwargs, draft_kwargs
            )
    disagg_cols = None
    if disagg:
        with mesh_context(env):
            disagg_cols = _disagg_pass(model, run_params, args, kv_kwargs)
    # SLO columns from the engine's telemetry histograms (ISSUE 7): the
    # warm-up pass's observations were dropped by reset_cache, so these
    # aggregate exactly the measured pass. TTFT is the prefill+graft
    # latency; TPOT covers the decode steps.
    ttft_h = eng.telemetry.histogram("serve_ttft_seconds")
    tpot_h = eng.telemetry.histogram("serve_tpot_seconds")
    lat = np.asarray(
        [dt for c in done for dt in c.token_latencies_s], np.float64
    )
    n_tokens = int(sum(len(c.tokens) - c.prompt_len for c in done))
    n_chips = len(jax.devices())
    tok_per_sec = n_tokens / wall
    chip = jax.devices()[0].device_kind
    per_chip = tok_per_sec / n_chips
    row = {
        "config": f"serve_bench_{args.preset}",
        "model": "gpt",
        "mesh": mesh_sizes,
        "param_sharding": "tp" if sharding == "sharded" else "replicated",
        "precision": "fp32",
        "grad_accum": 1,
        "remat": "none",
        "global_batch_size": args.slots,
        "per_chip_batch_size": args.slots,
        "n_chips": n_chips,
        "chip": chip,
        # Serving semantics: a "sample" is one generated token.
        "samples_per_sec_per_chip": round(per_chip, 3),
        "step_time_median_s": round(float(np.median(lat)), 6),
        "model_flops_per_sample": int(flops_per_token),
        "mfu": max(1e-9, flops_per_token * per_chip / _PEAK_FLOPS),
        "serving": {
            "arm": arm,
            "decode_attention": impl,
            "kv_cache_sharding": sharding,
            "kv_cache_quant": quant,
            "tokens_per_sec": round(tok_per_sec, 3),
            "latency_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "latency_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "ttft_s": round(ttft_h.quantile(0.50), 6),
            "ttft_p99_s": round(ttft_h.quantile(0.99), 6),
            "tpot_p50_s": round(tpot_h.quantile(0.50), 6),
            "tpot_p99_s": round(tpot_h.quantile(0.99), 6),
            "requests": len(work),
            "slots": args.slots,
            "cache_bucket": eng.bucket,
            "hbm_bytes_per_slot": bytes_per_slot,
            "bytes_per_slot_bf16_ref": bytes_bf16_ref,
            "max_slots_at_hbm": max_slots,
            "max_slots_at_hbm_bf16_ref": hbm_budget // max(bytes_bf16_ref, 1),
            "hbm_budget_gb": args.hbm_gb,
            # Per-request prefix SLO columns (every arm: 0 on bucketed).
            "prefix_hit_rate": round(
                sum(c.prefix_cache_hit for c in done) / len(done), 4
            ),
            "prefill_tokens_saved": int(
                sum(c.prefill_tokens_saved for c in done)
            ),
            # Speculative SLO columns (ISSUE 11; every arm — 1.0
            # invocations/token and 0.0 accept rate when speculate=off):
            # the per-request Completion.spec_accept_rate mean next to
            # the slot-level decode-invocations-per-emitted-token.
            "speculate": spec_mode if spec else "off",
            "disaggregated": disagg,
            "spec_accept_rate": round(
                sum(c.spec_accept_rate for c in done) / len(done), 4
            ),
            "decode_invocations_per_token": round(
                eng.stats["slot_steps"] / max(eng.stats["step_tokens"], 1),
                4,
            ),
            "engine_stats": dict(eng.stats),
            **({"paged": paged_cols} if paged_cols is not None else {}),
            **({"prefix": prefix} if prefix is not None else {}),
            **({"spec_repetitive": specd} if specd is not None else {}),
            **({"disagg": disagg_cols} if disagg_cols is not None else {}),
            **({"chaos": chaos} if chaos is not None else {}),
        },
        "note": (
            "continuous-batching serve bench (tools/serve_bench.py): "
            "tokens/sec and per-token latency through serving/engine.py; "
            "CPU-sim rows are diagnostics, the on-chip A/B at the "
            "gpt2_medium operating point is BACKLOG R8-1"
        ),
        "captured_at": datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    return row


def main(argv=None) -> int:
    args = _parse_args(argv)
    _setup_backend(args)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    if args.sim_devices:
        jax.config.update("jax_platforms", "cpu")

    model, params = _build(args.preset)
    flops = _decode_flops_per_token(model, params, args.slots)
    rows = []
    for arm in args.arms.split(","):
        arm = arm.strip()
        if not arm:
            continue
        row = run_arm(model, params, arm, args, flops)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.out:
        with open(args.out, "a") as fh:
            for row in rows:
                fh.write(json.dumps(row) + "\n")

    # Human-readable A/B summary.
    for row in rows:
        s = row["serving"]
        print(
            f"# {s['arm']:>23s}: {s['tokens_per_sec']:9.1f} tok/s  "
            f"p50 {s['latency_p50_ms']:7.2f} ms  "
            f"p99 {s['latency_p99_ms']:7.2f} ms  "
            f"{s['hbm_bytes_per_slot']:>9d} B/slot  "
            f"{s['max_slots_at_hbm']:>8d} slots@{s['hbm_budget_gb']:g}G",
            file=sys.stderr,
        )
        if "paged" in s:
            p = s["paged"]
            x = s["prefix"]
            print(
                f"# {'paged':>23s}: {p['block_bytes']:>6d} B/block  "
                f"peak {p['pool_peak_blocks']} blocks  "
                f"{p['hbm_bytes_per_active_slot']:>9d} B/active-slot  "
                f"prefix saved {x['prefill_tokens_saved']}/"
                f"{x['prompt_tokens_total']} tok over "
                f"{x['requests']} reqs ({x['unique_prefixes']} unique)",
                file=sys.stderr,
            )
        if "spec_repetitive" in s:
            sp = s["spec_repetitive"]
            print(
                f"# {'spec':>23s}: {sp['mode']} k={sp['k']}  "
                f"accept {sp['acceptance_rate']:.0%}  "
                f"{sp['mean_accepted_per_verify']:.2f} tok/verify  "
                f"{sp['decode_invocations_per_token']:.3f} inv/tok "
                f"({sp['invocations_reduction_x']:.2f}x fewer vs off)",
                file=sys.stderr,
            )
        if "disagg" in s:
            d = s["disagg"]
            print(
                f"# {'disagg':>23s}: decode TPOT p99 under burst "
                f"{d['disagg_decode_tpot_p99_ms']:.2f} ms vs colocated "
                f"{d['colocated_decode_tpot_p99_ms']:.2f} ms "
                f"({d['tail_isolation_x']:.2f}x isolation)  "
                f"{d['handoffs']} handoffs  "
                f"{d['handoff_transfer_bytes']} B moved  "
                f"{d['prefill_deferred']} deferred",
                file=sys.stderr,
            )
        if "chaos" in s:
            c = s["chaos"]
            print(
                f"# {'chaos':>23s}: shed {c['shed_rate']:.0%}  "
                f"deadline-miss {c['deadline_miss_rate']:.0%}  "
                f"quarantined {c['quarantined']}  "
                f"non-faulted p99 {c['nonfaulted_p99_ms']:.2f} ms",
                file=sys.stderr,
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
